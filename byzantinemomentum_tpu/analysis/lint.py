"""jaxlint — an AST rule engine for the JAX failure modes this codebase
actually has.

Each rule has a stable id (`BMT-Exx`), registers itself in `RULES`, and
yields `Violation`s over a parsed module. Detection is purely syntactic
(one `ast` pass, no jax import): the traced-scope rules lean on the
heuristic that a function is traced when it is decorated with / passed to
a tracing combinator (`jit`, `vmap`, `grad`, `lax.scan`, ...) or reachable
from one through same-module calls — exactly the discipline this codebase
follows (`engine/step.py` wires every traced function through
`_mode_jit`/`jax.jit`/`lax.scan` in the same module).

Suppression is per line and per rule, and the reason is mandatory:

    risky_line()  # bmt: noqa[BMT-E05] watchdog must survive mangled dirs

A `# bmt: noqa[...]` with an empty reason is itself reported (`BMT-E00`):
an unexplained suppression is technical debt with extra steps.

Output: `lint_paths` -> list of `Violation`; `format_human` /
`format_json` render them. The module is import-light on purpose — the
lint tier must run even where jax cannot initialize a backend.
"""

import ast
import dataclasses
import io
import json
import pathlib
import re
import tokenize

__all__ = ["RULES", "Violation", "lint_source", "lint_paths",
           "format_human", "format_json", "iter_python_files"]


# --------------------------------------------------------------------------- #
# Registry

@dataclasses.dataclass(frozen=True)
class Rule:
    id: str        # "BMT-E05"
    slug: str      # "broad-except"
    summary: str   # one line for the --rules table
    check: object  # callable(Module) -> iterable[Violation]
    # Driver rules register an id (for the --rules table and BMT-E00
    # unknown-id validation) but fire from their own whole-program
    # driver, not the per-module pass — so BMT-E09 cannot decide whether
    # a suppression naming one is dead and must skip it.
    driver: bool = False


@dataclasses.dataclass(frozen=True)
class Violation:
    path: str
    line: int
    col: int
    rule: str
    message: str

    def as_dict(self):
        return dataclasses.asdict(self)


RULES = {}


def rule(rule_id, slug, summary, driver=False):
    def wrap(fn):
        RULES[rule_id] = Rule(rule_id, slug, summary, fn, driver)
        return fn
    return wrap


# --------------------------------------------------------------------------- #
# Shared syntactic helpers

def _dotted(node):
    """`a.b.c` -> "a.b.c" for Name/Attribute chains (None otherwise)."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _terminal(node):
    """The terminal callable name of an expression: `self._mode_jit` ->
    "_mode_jit", `functools.partial(f, x)` -> terminal of `f` (partials
    forward to their wrapped callable)."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Call) and node.args:
        if _terminal(node.func) == "partial":
            return _terminal(node.args[0])
    return None


# Names that mean "the arguments of this call get traced"
_TRACING_NAMES = frozenset({
    "jit", "pjit", "vmap", "pmap", "grad", "value_and_grad", "jacfwd",
    "jacrev", "hessian", "scan", "while_loop", "fori_loop", "cond",
    "switch", "associated_scan", "shard_map", "remat", "checkpoint",
    "custom_jvp", "custom_vjp", "linearize", "vjp", "jvp",
})


def _is_tracing_callee(name):
    return name is not None and (name in _TRACING_NAMES or "jit" in name)


_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


class Module:
    """One parsed file plus the shared analyses every rule reads."""

    def __init__(self, path, source):
        self.path = str(path)
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=self.path)
        self.parent = {}
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                self.parent[child] = node
        # name -> defs: every def in the module, by name (methods included)
        self.defs = {}
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.defs.setdefault(node.name, []).append(node)
        # simple aliases: `worker = self._worker_grad` / `w = partial(f, x)`
        self.alias = {}
        for node in ast.walk(self.tree):
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)):
                term = _terminal(node.value)
                if term is not None and not isinstance(node.value, ast.Name):
                    self.alias[node.targets[0].id] = term
        self.traced = self._traced_functions()
        self.noqa = self._noqa_lines()

    # -- traced-scope inference ------------------------------------------- #

    def _mark_traced_arg(self, arg, traced):
        if isinstance(arg, ast.Lambda):
            traced.add(arg)
            return
        term = _terminal(arg)
        term = self.alias.get(term, term)
        for d in self.defs.get(term, ()):
            traced.add(d)

    def _traced_functions(self):
        traced = set()
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for deco in node.decorator_list:
                    names = {_terminal(deco)}
                    if isinstance(deco, ast.Call):
                        names.add(_terminal(deco.func))
                        names.update(_terminal(a) for a in deco.args)
                    if any(_is_tracing_callee(n) for n in names if n):
                        traced.add(node)
            if isinstance(node, ast.Call):
                if _is_tracing_callee(_terminal(node.func)):
                    for arg in node.args:
                        self._mark_traced_arg(arg, traced)
                    # Keyword-passed bodies are traced exactly like
                    # positional ones: `shard_map(f=kernel, mesh=...)`,
                    # `while_loop(cond_fun=c, body_fun=b, ...)` — the
                    # compat-wrapper idiom (`parallel/mesh.py`) takes the
                    # body positionally, but call sites that name it must
                    # not hide the scope from BMT-E02/E06. Non-callable
                    # keywords (mesh=, in_specs=, static_argnums=) have no
                    # same-module def and mark nothing.
                    for kw in node.keywords:
                        self._mark_traced_arg(kw.value, traced)
        # Fixpoint: nested defs and same-module callees of traced code are
        # traced too (the engine's phase helpers, the kernels they call)
        changed = True
        while changed:
            changed = False
            for fn in list(traced):
                body = fn.body if isinstance(fn.body, list) else [fn.body]
                for stmt in body:
                    for node in ast.walk(stmt):
                        if isinstance(node, _FUNC_NODES) and node not in traced:
                            traced.add(node)
                            changed = True
                        if isinstance(node, ast.Call):
                            term = _terminal(node.func)
                            term = self.alias.get(term, term)
                            for d in self.defs.get(term, ()):
                                if d not in traced:
                                    traced.add(d)
                                    changed = True
        return traced

    def enclosing_function(self, node):
        cur = self.parent.get(node)
        while cur is not None and not isinstance(cur, _FUNC_NODES):
            cur = self.parent.get(cur)
        return cur

    def in_traced(self, node):
        cur = self.enclosing_function(node)
        while cur is not None:
            if cur in self.traced:
                return True
            cur = self.enclosing_function(cur)
        return False

    def function_scopes(self):
        """Every def/lambda body plus the module body, as (scope_node,
        statements) pairs — the unit the dataflow-ish rules walk."""
        yield self.tree, self.tree.body
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield node, node.body

    def scope_nodes(self, scope):
        """All AST nodes belonging to `scope` but not to a nested def/class
        (so a name in an inner closure does not count as a use in the
        outer scope's straight line)."""
        own = []
        stack = list(scope.body)
        while stack:
            node = stack.pop()
            own.append(node)
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue  # its name binds here; its body is another scope
            stack.extend(ast.iter_child_nodes(node))
        return own

    # -- suppression ------------------------------------------------------ #

    _NOQA = re.compile(r"#\s*bmt:\s*noqa\[([A-Za-z0-9_\-, ]+)\]\s*(.*\S)?")

    def _noqa_lines(self):
        """line -> (set of rule ids, reason or None). Real comments only
        (tokenize): a noqa example quoted in a docstring is prose, not a
        suppression."""
        table = {}
        try:
            tokens = tokenize.generate_tokens(
                io.StringIO(self.source).readline)
            comments = [(t.start[0], t.string) for t in tokens
                        if t.type == tokenize.COMMENT]
        except (tokenize.TokenError, IndentationError, SyntaxError):
            comments = list(enumerate(self.lines, start=1))
        for line, text in comments:
            m = self._NOQA.search(text)
            if m:
                ids = {t.strip() for t in m.group(1).split(",") if t.strip()}
                reason = (m.group(2) or "").strip() or None
                table[line] = (ids, reason)
        return table


# --------------------------------------------------------------------------- #
# BMT-E00 — suppressions must explain themselves

@rule("BMT-E00", "noqa-without-reason",
      "a `# bmt: noqa[...]` suppression carries no reason")
def _check_noqa_reason(mod):
    out = []
    for line, (ids, reason) in sorted(mod.noqa.items()):
        if reason is None:
            out.append(Violation(
                mod.path, line, 0, "BMT-E00",
                f"suppression of {', '.join(sorted(ids))} without a reason "
                f"— write `# bmt: noqa[RULE] why this is safe`"))
        unknown = sorted(i for i in ids if i not in RULES and i != "all")
        if unknown:
            out.append(Violation(
                mod.path, line, 0, "BMT-E00",
                f"suppression names unknown rule id(s) "
                f"{', '.join(unknown)}"))
    return out


# --------------------------------------------------------------------------- #
# BMT-E01 — PRNG key reuse

# jax.random calls that DERIVE without consuming; everything else under
# jax.random consumes its key argument
_KEY_DERIVERS = frozenset({
    "split", "fold_in", "PRNGKey", "key", "key_data", "wrap_key_data",
    "clone", "key_impl",
})
_RANDOM_MODULES = frozenset({"random", "jrandom", "jr"})


def _random_sampler_call(node):
    """The (call, key-arg) of a consuming `jax.random.<fn>(key, ...)` call,
    else None."""
    if not isinstance(node, ast.Call):
        return None
    func = node.func
    if not isinstance(func, ast.Attribute):
        return None
    owner = _terminal(func.value)
    if owner not in _RANDOM_MODULES or func.attr in _KEY_DERIVERS:
        return None
    if not node.args:
        return None
    return node.args[0]


def _field_of(parent, child):
    for field, value in ast.iter_fields(parent):
        if value is child or (isinstance(value, list) and child in value):
            return field
    return None


def _control_context(mod, node, scope):
    """(branch_path, exits) of a node inside `scope`: which field of each
    enclosing If/Try/loop the node sits in (so mutually exclusive branches
    don't pair), and whether control leaves the function right after the
    node (a `return`/`raise` use cannot flow into a later one)."""
    path, exits = {}, False
    cur = node
    parent = mod.parent.get(cur)
    while parent is not None and cur is not scope:
        if isinstance(parent, (ast.Return, ast.Raise)):
            exits = True
        if isinstance(parent, (ast.If, ast.Try, ast.For, ast.While)):
            path[id(parent)] = _field_of(parent, cur)
        cur, parent = parent, mod.parent.get(parent)
    return path, exits


def _may_flow_between(ctx_a, ctx_b):
    """Whether execution can reach use B after use A in one run — False
    when A exits the function or the two sit in different branches of a
    shared If/Try."""
    path_a, exits_a = ctx_a
    path_b, _ = ctx_b
    if exits_a:
        return False
    return all(path_a[k] == path_b[k] for k in path_a.keys() & path_b.keys())


@rule("BMT-E01", "prng-key-reuse",
      "the same PRNG key is consumed by two sampling calls (split it)")
def _check_key_reuse(mod):
    out = []
    for scope, _ in mod.function_scopes():
        consumes = {}   # name -> [(lineno, node)...]
        assigns = {}    # name -> [lineno...]
        nodes = mod.scope_nodes(scope)
        for node in nodes:
            key = _random_sampler_call(node)
            if key is not None and isinstance(key, ast.Name):
                consumes.setdefault(key.id, []).append((key.lineno, node))
            if isinstance(node, ast.Name) and isinstance(
                    node.ctx, (ast.Store,)):
                assigns.setdefault(node.id, []).append(node.lineno)
        for name, uses in consumes.items():
            uses = sorted(uses, key=lambda u: u[0])
            marks = sorted(assigns.get(name, ()))
            ctxs = [_control_context(mod, n, scope) for _, n in uses]
            # straight-line double consumption without a reassignment
            for i in range(len(uses) - 1):
                (a, _), (b, _) = uses[i], uses[i + 1]
                if any(a < m <= b for m in marks):
                    continue
                if not _may_flow_between(ctxs[i], ctxs[i + 1]):
                    continue
                out.append(Violation(
                    mod.path, b, 0, "BMT-E01",
                    f"PRNG key {name!r} already consumed on line {a}; "
                    f"derive a fresh key with jax.random.split/fold_in"))
            # a single consumption inside a loop whose key never rebinds
            # in the body consumes the same key every iteration
            for (use, node), (path, exits) in zip(uses, ctxs):
                if exits:
                    continue  # returns out of the loop on first draw
                loop = _enclosing_loop(mod, node, scope)
                if loop is None:
                    continue
                body_lines = {n.lineno for n in ast.walk(loop)
                              if hasattr(n, "lineno")}
                if not any(m in body_lines for m in marks):
                    out.append(Violation(
                        mod.path, use, 0, "BMT-E01",
                        f"PRNG key {name!r} consumed inside a loop without "
                        f"rebinding — every iteration samples identically"))
    return out


def _ancestors(mod, node, scope):
    cur = mod.parent.get(node)
    while cur is not None and cur is not scope:
        yield cur
        cur = mod.parent.get(cur)


def _enclosing_loop(mod, node, scope):
    for anc in _ancestors(mod, node, scope):
        if isinstance(anc, (ast.For, ast.While, ast.AsyncFor)):
            return anc
    return None


# --------------------------------------------------------------------------- #
# BMT-E02 — host synchronization inside traced scopes

_NP_SAFE = frozenset({
    # static/metadata numpy uses that never materialize a tracer
    "float32", "float64", "float16", "int32", "int64", "uint8", "uint32",
    "bool_", "dtype", "finfo", "iinfo", "pi", "e", "inf", "nan", "newaxis",
    "prod", "ndim", "shape", "issubdtype", "promote_types", "result_type",
})


@rule("BMT-E02", "host-sync-in-trace",
      "host synchronization (.item()/float()/np.*) inside a traced scope")
def _check_host_sync(mod):
    out = []
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call) or not mod.in_traced(node):
            continue
        func = node.func
        # x.item() — the canonical device sync
        if (isinstance(func, ast.Attribute) and func.attr == "item"
                and not node.args):
            out.append(Violation(
                mod.path, node.lineno, node.col_offset, "BMT-E02",
                ".item() inside a traced function synchronizes the host "
                "(and fails on tracers) — keep the value on device"))
            continue
        # np.<fn>(...) on traced values runs at trace time on the host
        if isinstance(func, ast.Attribute):
            owner = _terminal(func.value)
            if (owner in ("np", "numpy") and func.attr not in _NP_SAFE):
                out.append(Violation(
                    mod.path, node.lineno, node.col_offset, "BMT-E02",
                    f"np.{func.attr}(...) inside a traced function "
                    f"materializes on the host — use jnp"))
                continue
        # float()/int()/bool() on a traced-function parameter or a
        # jax/jnp-producing call forces concretization
        if isinstance(func, ast.Name) and func.id in ("float", "int",
                                                      "bool") and node.args:
            arg = node.args[0]
            enclosing = mod.enclosing_function(node)
            params = set()
            cur = enclosing
            while cur is not None:
                if isinstance(cur, _FUNC_NODES):
                    a = cur.args
                    for p in (list(a.posonlyargs) + list(a.args)
                              + list(a.kwonlyargs)):
                        params.add(p.arg)
                    if a.vararg:
                        params.add(a.vararg.arg)
                cur = mod.enclosing_function(cur)
            suspect = (isinstance(arg, ast.Name) and arg.id in params
                       and arg.id != "self")
            if isinstance(arg, ast.Call):
                owner = None
                if isinstance(arg.func, ast.Attribute):
                    owner = _dotted(arg.func.value)
                suspect = suspect or (owner or "").split(".")[0] in (
                    "jnp", "jax", "lax")
            if suspect:
                out.append(Violation(
                    mod.path, node.lineno, node.col_offset, "BMT-E02",
                    f"{func.id}() on a traced value concretizes at trace "
                    f"time — pass it as data or use jnp casts"))
    return out


# --------------------------------------------------------------------------- #
# BMT-E03 — jit cache-miss hazards

@rule("BMT-E03", "jit-cache-miss",
      "re-wrapping jit inside a loop (or jit of a fresh partial/lambda "
      "per call) defeats the compile cache")
def _check_cache_miss(mod):
    out = []
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        name = _terminal(node.func)
        if name not in ("jit", "pjit"):
            continue
        # jit(...) syntactically inside a for/while body: a fresh wrapper
        # (and for lambdas a fresh cache key) every iteration
        cur = mod.parent.get(node)
        in_loop = False
        while cur is not None:
            if isinstance(cur, (ast.For, ast.While, ast.AsyncFor)):
                in_loop = True
                break
            if isinstance(cur, _FUNC_NODES):
                break  # the loop would be outside the enclosing function
            cur = mod.parent.get(cur)
        if in_loop:
            out.append(Violation(
                mod.path, node.lineno, node.col_offset, "BMT-E03",
                "jax.jit(...) inside a loop body builds a fresh wrapper "
                "every iteration — hoist the jitted function out"))
            continue
        # jit(functools.partial(...)): partial objects hash by identity,
        # so a re-executed construction site recompiles every time
        wrapped = node.args[0] if node.args else None
        if (isinstance(wrapped, ast.Call)
                and _terminal(wrapped.func) == "partial"
                and mod.enclosing_function(node) is not None):
            out.append(Violation(
                mod.path, node.lineno, node.col_offset, "BMT-E03",
                "jit(partial(...)) built inside a function keys the "
                "compile cache on a fresh partial object per call — use "
                "static_argnums or close over the constant"))
    return out


# --------------------------------------------------------------------------- #
# BMT-E04 — use after donation

def _donated_positions(call):
    """The donate_argnums literal of a jit call, as a set of ints."""
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                return {v.value}
            if isinstance(v, (ast.Tuple, ast.List)):
                return {e.value for e in v.elts
                        if isinstance(e, ast.Constant)
                        and isinstance(e.value, int)}
    return set()


@rule("BMT-E04", "use-after-donate",
      "a buffer passed at a donate_argnums position is read after the call")
def _check_use_after_donate(mod):
    out = []
    for scope, _ in mod.function_scopes():
        nodes = mod.scope_nodes(scope)
        donators = {}  # local name -> donated positions
        for node in nodes:
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and isinstance(node.value, ast.Call)
                    and _terminal(node.value.func) in ("jit", "pjit")):
                pos = _donated_positions(node.value)
                if pos:
                    donators[node.targets[0].id] = pos
        if not donators:
            continue
        donated_uses = []  # (varname, call lineno)
        for node in nodes:
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id in donators):
                for p in donators[node.func.id]:
                    if p < len(node.args) and isinstance(
                            node.args[p], ast.Name):
                        donated_uses.append(
                            (node.args[p].id, node.lineno))
        for name, call_line in donated_uses:
            rebinds = [n.lineno for n in nodes
                       if isinstance(n, ast.Name) and n.id == name
                       and isinstance(n.ctx, ast.Store)]
            for n in nodes:
                if (isinstance(n, ast.Name) and n.id == name
                        and isinstance(n.ctx, ast.Load)
                        and n.lineno > call_line
                        and not any(call_line < r <= n.lineno
                                    for r in rebinds)):
                    out.append(Violation(
                        mod.path, n.lineno, n.col_offset, "BMT-E04",
                        f"{name!r} was donated on line {call_line} "
                        f"(donate_argnums) — its buffer is dead here"))
                    break  # one report per donation site is enough
    return out


# --------------------------------------------------------------------------- #
# BMT-E05 — broad or bare except

def _except_names(handler):
    t = handler.type
    if t is None:
        return {"<bare>"}
    elts = t.elts if isinstance(t, ast.Tuple) else [t]
    return {_terminal(e) for e in elts}


@rule("BMT-E05", "broad-except",
      "bare `except:` / `except Exception` — narrow it or annotate why")
def _check_broad_except(mod):
    out = []
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        names = _except_names(node)
        if "<bare>" in names or "BaseException" in names:
            out.append(Violation(
                mod.path, node.lineno, node.col_offset, "BMT-E05",
                "bare/BaseException except masks KeyboardInterrupt and "
                "SystemExit — catch Exception at the very most"))
        elif "Exception" in names:
            out.append(Violation(
                mod.path, node.lineno, node.col_offset, "BMT-E05",
                "except Exception eats every fault the resilience stack "
                "should surface — narrow it, or annotate the reason"))
    return out


# --------------------------------------------------------------------------- #
# BMT-E06 — wall clock inside traced scopes

_WALL_CLOCK = frozenset({
    "time.time", "time.monotonic", "time.perf_counter",
    "time.process_time", "time.time_ns", "time.monotonic_ns",
    "time.perf_counter_ns", "datetime.now", "datetime.utcnow",
    "datetime.datetime.now", "datetime.datetime.utcnow",
})


@rule("BMT-E06", "wall-clock-in-trace",
      "time.time()/perf_counter() inside a traced function is a "
      "trace-time constant, not a per-step clock")
def _check_wall_clock(mod):
    out = []
    for node in ast.walk(mod.tree):
        if (isinstance(node, ast.Call) and _dotted(node.func) in _WALL_CLOCK
                and mod.in_traced(node)):
            out.append(Violation(
                mod.path, node.lineno, node.col_offset, "BMT-E06",
                f"{_dotted(node.func)}() in a traced function freezes to "
                f"its trace-time value — time on the host, around the "
                f"dispatch"))
    return out


# --------------------------------------------------------------------------- #
# BMT-E07 — redundant array conversions

_PRODUCERS = frozenset({
    "asarray", "array", "stack", "concatenate", "zeros", "ones", "full",
    "arange", "linspace", "zeros_like", "ones_like", "full_like",
})
_STACKERS = frozenset({"stack", "concatenate", "vstack", "hstack"})


_ARRAY_FAMILY = {"jnp": "jnp", "jax.numpy": "jnp", "np": "np",
                 "numpy": "np"}


def _array_call(node, names):
    """The array-library family ("jnp"/"np") of a call `jnp.<fn>`/
    `np.<fn>` with fn in names, else None. A conversion is only redundant
    within one family: `jnp.asarray(np.stack(...))` is a host->device
    move, not a double conversion."""
    if not (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)):
        return None
    if node.func.attr not in names:
        return None
    return _ARRAY_FAMILY.get(_dotted(node.func.value))


@rule("BMT-E07", "redundant-conversion",
      "asarray of something already an array of the same library "
      "(double conversion)")
def _check_redundant_conversion(mod):
    out = []
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        # jnp.asarray(jnp.stack(...)) — the inner call already produced
        # an array (a dtype= kwarg makes the outer call a cast: fine)
        fam = _array_call(node, ("asarray", "array"))
        if (fam is not None and not node.keywords and len(node.args) == 1
                and _array_call(node.args[0], _PRODUCERS) == fam):
            out.append(Violation(
                mod.path, node.lineno, node.col_offset, "BMT-E07",
                "asarray of a call that already produced an array — a "
                "redundant conversion"))
        # jnp.stack([jnp.asarray(g) for g in ...]) — stack converts its
        # inputs itself (the `ops/as_matrix` double conversion)
        fam = _array_call(node, _STACKERS)
        if fam is not None and node.args:
            arg = node.args[0]
            elts = ()
            if isinstance(arg, (ast.List, ast.Tuple)):
                elts = arg.elts
            elif isinstance(arg, (ast.ListComp, ast.GeneratorExp)):
                elts = (arg.elt,)
            if elts and all(
                    _array_call(e, ("asarray", "array")) == fam
                    and not e.keywords for e in elts):
                out.append(Violation(
                    mod.path, node.lineno, node.col_offset, "BMT-E07",
                    f"{node.func.attr} already converts its inputs — the "
                    f"per-element asarray is a redundant conversion"))
    return out


# --------------------------------------------------------------------------- #
# BMT-E08 — dynamic trace-annotation names

_SCOPE_CALLEES = frozenset({"named_scope", "TraceAnnotation",
                            "StepTraceAnnotation"})


def _is_dynamic_string(node):
    """Whether an expression builds its string per call: an f-string with
    interpolations, a `.format(...)` call, a `%` format, or a `+`
    concatenation involving any of those. A constant (or an f-string with
    no placeholders) is static."""
    if isinstance(node, ast.JoinedStr):
        return any(isinstance(v, ast.FormattedValue) for v in node.values)
    if (isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute)
            and node.func.attr == "format"):
        return True
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Mod):
        return isinstance(node.left, (ast.Constant, ast.JoinedStr))
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
        return (_is_dynamic_string(node.left)
                or _is_dynamic_string(node.right))
    return False


@rule("BMT-E08", "dynamic-scope-name",
      "a formatted (f-string/.format) jax.named_scope/TraceAnnotation "
      "name inside a traced scope — per-step name churn pollutes trace "
      "metadata and hashes a fresh cache key per call")
def _check_dynamic_scope_name(mod):
    out = []
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call) or not node.args:
            continue
        if _terminal(node.func) not in _SCOPE_CALLEES:
            continue
        if not mod.in_traced(node):
            continue
        if _is_dynamic_string(node.args[0]):
            out.append(Violation(
                mod.path, node.lineno, node.col_offset, "BMT-E08",
                f"{_terminal(node.func)}(...) name is built per call — "
                f"every trace gets fresh metadata (and the phase "
                f"attribution in obs/attrib cannot bucket it); use a "
                f"static name"))
    return out


# --------------------------------------------------------------------------- #
# BMT-E10 — synchronization primitives allocated on hot paths

_SYNC_FACTORIES = frozenset({
    "Lock", "RLock", "Condition", "Event", "Semaphore", "BoundedSemaphore",
    "Barrier",
})


@rule("BMT-E10", "lock-in-hot-path",
      "threading.Lock()/Condition()/... constructed inside a traced "
      "scope or a loop body — per-call allocation churn, and useless "
      "under jit (the trace captures one construction, not a guard)")
def _check_lock_in_hot_path(mod):
    out = []
    for node in ast.walk(mod.tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and _terminal(node.func.value) == "threading"
                and node.func.attr in _SYNC_FACTORIES):
            continue
        if mod.in_traced(node):
            out.append(Violation(
                mod.path, node.lineno, node.col_offset, "BMT-E10",
                f"threading.{node.func.attr}() inside a traced scope — "
                f"the primitive is created at trace time and guards "
                f"nothing at run time; synchronize on the host, outside "
                f"the trace"))
            continue
        scope = mod.enclosing_function(node) or mod.tree
        if _enclosing_loop(mod, node, scope) is not None:
            out.append(Violation(
                mod.path, node.lineno, node.col_offset, "BMT-E10",
                f"threading.{node.func.attr}() constructed inside a loop "
                f"body — one primitive per iteration guards nothing "
                f"across iterations (and churns allocations on a hot "
                f"path); hoist it to __init__/module scope"))
    return out


# --------------------------------------------------------------------------- #
# BMT-E11 — check-then-act lazy init inside a traced scope

def _module_global_names(mod):
    names = set()
    for node in mod.tree.body:
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
            targets = [node.target]
        else:
            continue
        for t in targets:
            if isinstance(t, ast.Name):
                names.add(t.id)
            elif isinstance(t, (ast.Tuple, ast.List)):
                names.update(e.id for e in t.elts
                             if isinstance(e, ast.Name))
    return names


def _assigns_name(body, name):
    for node in body:
        for sub in ast.walk(node):
            if (isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Store)
                    and sub.id == name):
                return True
    return False


def _stores_subscript(body, dotted):
    for node in body:
        for sub in ast.walk(node):
            if (isinstance(sub, ast.Subscript)
                    and isinstance(sub.ctx, ast.Store)
                    and _dotted(sub.value) == dotted):
                return True
    return False


@rule("BMT-E11", "lazy-init-in-trace",
      "check-then-act lazy initialization (`if x is None: x = ...` / "
      "`if k not in cache: cache[k] = ...`) inside a traced scope — the "
      "check evaluates once at trace time, so the fill is baked into "
      "the jaxpr (or silently skipped on replay) and the unlocked "
      "read-test-write is a data race besides")
def _check_lazy_init_in_trace(mod):
    out = []
    globals_ = _module_global_names(mod)
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.If) or not mod.in_traced(node):
            continue
        test = node.test
        if not (isinstance(test, ast.Compare) and len(test.ops) == 1):
            continue
        op = test.ops[0]
        if (isinstance(op, ast.Is)
                and isinstance(test.comparators[0], ast.Constant)
                and test.comparators[0].value is None):
            target = test.left
            if (isinstance(target, ast.Name) and target.id in globals_
                    and _assigns_name(node.body, target.id)):
                out.append(Violation(
                    mod.path, node.lineno, node.col_offset, "BMT-E11",
                    f"lazy init of module global {target.id!r} in a "
                    f"traced scope — the None-check evaluates once at "
                    f"trace time; initialize eagerly at import, or hoist "
                    f"the fill out of the traced function"))
        elif isinstance(op, ast.NotIn):
            container = _dotted(test.comparators[0])
            if container is None:
                continue
            root = container.split(".")[0]
            if ((root in globals_ or root == "self")
                    and _stores_subscript(node.body, container)):
                out.append(Violation(
                    mod.path, node.lineno, node.col_offset, "BMT-E11",
                    f"check-then-act cache fill on {container!r} in a "
                    f"traced scope — the membership test traces once and "
                    f"the store is a hidden side effect under jit; "
                    f"populate the cache outside the trace"))
    return out


# --------------------------------------------------------------------------- #
# BMT-E09 — dead suppressions (annotations must not rot)

@rule("BMT-E09", "dead-noqa",
      "a `# bmt: noqa[RULE]` whose RULE no longer fires on that line "
      "(the annotation rotted; drop it)")
def _check_dead_noqa(mod):
    # Driver-implemented: deciding deadness needs every OTHER rule's
    # pre-suppression hits for the line, which only `lint_source` holds.
    return ()


def _dead_noqa_violations(mod, selected, fired):
    """BMT-E09 hits: suppressions naming a rule that was RUN this pass
    (`selected`) but did not fire on the line (`fired`: line -> rule ids).
    `all`-suppressions and unknown ids are out of scope (the latter are
    BMT-E00's)."""
    checkable = {rid for rid in selected if rid not in
                 ("BMT-E00", "BMT-E09") and not selected[rid].driver}
    out = []
    for line, (ids, _reason) in sorted(mod.noqa.items()):
        for rid in sorted(ids):
            if rid in checkable and rid not in fired.get(line, ()):
                out.append(Violation(
                    mod.path, line, 0, "BMT-E09",
                    f"dead suppression: {rid} does not fire on this line "
                    f"anymore — drop the noqa (a rotten annotation hides "
                    f"the next real violation)"))
    return out


# --------------------------------------------------------------------------- #
# Driver

def lint_source(source, path="<string>", rules=None):
    """Lint one source string; returns the unsuppressed violations plus
    the suppression-hygiene findings (BMT-E00 reasons, BMT-E09 dead
    noqas)."""
    try:
        mod = Module(path, source)
    except SyntaxError as err:
        return [Violation(str(path), err.lineno or 0, 0, "BMT-E00",
                          f"file does not parse: {err.msg}")]
    selected = RULES if rules is None else {
        k: v for k, v in RULES.items() if k in rules}
    raw = []
    for r in selected.values():
        raw.extend(r.check(mod))
    fired = {}  # line -> rule ids that fired there (pre-suppression)
    for v in raw:
        fired.setdefault(v.line, set()).add(v.rule)
    if "BMT-E09" in selected:
        raw.extend(_dead_noqa_violations(mod, selected, fired))
    out = []
    for v in raw:
        ids_reason = mod.noqa.get(v.line)
        if ids_reason is not None and v.rule != "BMT-E00":
            ids, reason = ids_reason
            if (v.rule in ids or "all" in ids) and reason:
                continue  # suppressed, with a reason (E00 checks it)
        out.append(v)
    return sorted(out, key=lambda v: (v.path, v.line, v.rule))


def iter_python_files(paths):
    for p in paths:
        p = pathlib.Path(p)
        if p.is_dir():
            yield from sorted(q for q in p.rglob("*.py")
                              if "__pycache__" not in q.parts)
        elif p.suffix == ".py":
            yield p


def lint_paths(paths, rules=None):
    out = []
    for f in iter_python_files(paths):
        out.extend(lint_source(
            f.read_text(encoding="utf-8"), path=str(f), rules=rules))
    return out


def format_human(violations):
    lines = [f"{v.path}:{v.line}:{v.col}: {v.rule} {v.message}"
             for v in violations]
    lines.append(f"jaxlint: {len(violations)} violation"
                 f"{'' if len(violations) == 1 else 's'}")
    return "\n".join(lines)


def format_json(violations, files_checked=None):
    counts = {}
    for v in violations:
        counts[v.rule] = counts.get(v.rule, 0) + 1
    payload = {"violations": [v.as_dict() for v in violations],
               "counts": counts}
    if files_checked is not None:
        payload["files"] = files_checked
    return json.dumps(payload, indent=2, sort_keys=True)
