"""CLI: `python -m byzantinemomentum_tpu.analysis <paths...>` lints
(jaxlint BMT-E rules AND the BMT-T concurrency rules — both AST
families run in one pass); `--check-lowerings` runs the lattice drift
gate (StableHLO fingerprints + BMT-H structural lint over every
enumerated cell); `--check-locks` runs the whole-program BMT-L sweep
(interprocedural lock-order graph + deadlock/blocking rules) and gates
the blessed hierarchy (`tests/goldens/locks.json`);
`--schedule-smoke` runs the deterministic interleaving harness's
selfcheck (the planted serve-counter lost-update must be found; the
fixed pattern must be schedule-clean); `--rules` prints all four
registries (E, H, T, L) in one table. Exit 0 = clean (or incomparable
goldens), 1 = violations/drift/failed smoke, 2 = usage error."""

import argparse
import json
import sys

# Importing the package registers the BMT-T rules beside the E-rules
from byzantinemomentum_tpu.analysis import hlolint, lint


def _print_rules():
    """All registries, one table: the AST rules over source (jaxlint
    BMT-E + the BMT-T concurrency contracts, one registry) and the
    structural rules (BMT-H) over lowered programs."""
    rules = {**lint.RULES, **hlolint.HLO_RULES}
    width = max(len(r.slug) for r in rules.values())
    for rule_id in sorted(rules):
        r = rules[rule_id]
        print(f"{r.id}  {r.slug:<{width}}  {r.summary}")


def _check_lowerings(goldens, as_json):
    # Pin the CPU backend for deterministic fingerprints (this
    # environment's sitecustomize may force a TPU platform; see
    # tests/conftest.py for why the config update is load-bearing), and
    # force the virtual host device count the mesh lattice cells need —
    # both only effective before jax initializes its backend
    import os
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    import jax
    jax.config.update("jax_platforms", "cpu")
    from byzantinemomentum_tpu.analysis import lowering

    report = lowering.check(goldens) if goldens else lowering.check()
    if as_json:
        print(json.dumps(report, indent=2, sort_keys=True))
    else:
        print(f"lowerings: {report['status']}"
              + (f" ({report.get('checked', 0)} cells)"
                 if "checked" in report else ""))
        for key in ("drifted", "added", "removed"):
            for cell in report.get(key, ()):
                print(f"  {key}: {cell}")
        for v in report.get("violations", ()):
            print(f"  {v['path']}:{v['line']}: {v['rule']} {v['message']}")
        if report["status"] == "missing":
            print(f"  no goldens at {report['path']} — run "
                  f"scripts/bless_lowerings.py")
        if report["status"] == "incomparable":
            print(f"  blessed under {report['blessed']}, running "
                  f"{report['current']} — re-bless, not a drift failure")
    # missing goldens fail (the gate would silently pass forever);
    # incomparable does not (toolchain bump, the bench_compare discipline)
    return 0 if report["status"] in ("ok", "incomparable") else 1


def _check_locks(goldens, as_json):
    from byzantinemomentum_tpu.analysis import locks

    report = (locks.check(goldens) if goldens else locks.check())
    if as_json:
        print(json.dumps(report, indent=2, sort_keys=True))
    else:
        print(f"locks: {report['status']} ({report['locks']} locks, "
              f"{report['edges']} edges, {report['cycles']} cycles, "
              f"{report['files']} files, "
              f"{report['suppressed']} suppressed)")
        for v in report["violations"]:
            print(f"  {v['path']}:{v['line']}: {v['rule']} {v['message']}")
        for key, items in sorted(report.get("drift", {}).items()):
            for item in items:
                print(f"  {key}: {item}")
        if report["status"] == "missing":
            print("  no goldens — run scripts/bless_locks.py")
        if report["status"] == "incomparable":
            print(f"  blessed under python {report['blessed_python']} — "
                  f"re-bless, not a drift failure")
    # Same stance as the lowering gate: missing goldens fail,
    # incomparable (toolchain bump) does not — but violations always do.
    return 0 if report["ok"] else 1


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="python -m byzantinemomentum_tpu.analysis",
        description="jaxlint + lowering-contract gate")
    parser.add_argument("paths", nargs="*",
                        help="files/directories to lint")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable output")
    parser.add_argument("--rules", action="store_true",
                        help="print the rule registry and exit")
    parser.add_argument("--check-lowerings", action="store_true",
                        help="compare StableHLO fingerprints against the "
                             "blessed goldens")
    parser.add_argument("--check-locks", action="store_true",
                        help="run the whole-program BMT-L lock sweep and "
                             "compare the lock-order graph against the "
                             "blessed hierarchy (tests/goldens/locks.json)")
    parser.add_argument("--schedule-smoke", action="store_true",
                        help="run the interleaving-harness selfcheck "
                             "(analysis/schedule.py): the planted "
                             "lost-update is found, the fixed counter is "
                             "schedule-clean")
    parser.add_argument("--goldens", default=None,
                        help="override the goldens path "
                             "(default tests/goldens/lowerings.json)")
    args = parser.parse_args(argv)

    if args.rules:
        _print_rules()
        return 0
    if (not args.paths and not args.check_lowerings
            and not args.check_locks and not args.schedule_smoke):
        parser.error("nothing to do: give paths to lint, "
                     "--check-lowerings, --check-locks, "
                     "--schedule-smoke, or --rules")

    rc = 0
    if args.paths:
        files = list(lint.iter_python_files(args.paths))
        violations = lint.lint_paths(args.paths)
        if args.json:
            print(lint.format_json(violations, files_checked=len(files)))
        else:
            print(lint.format_human(violations))
        rc = 1 if violations else rc
    if args.check_lowerings:
        rc = max(rc, _check_lowerings(args.goldens, args.json))
    if args.check_locks:
        # `--goldens` belongs to whichever single gate runs; when both
        # gates run it keeps its documented meaning (lowerings).
        override = args.goldens if not args.check_lowerings else None
        rc = max(rc, _check_locks(override, args.json))
    if args.schedule_smoke:
        from byzantinemomentum_tpu.analysis import schedule
        report = schedule.selfcheck()
        # One parseable line (the lint tier records it) + human detail
        print("schedule: " + json.dumps(report, sort_keys=True))
        rc = max(rc, 0 if report["ok"] else 1)
    return rc


if __name__ == "__main__":
    sys.exit(main())
