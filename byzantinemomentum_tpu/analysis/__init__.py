"""Static analysis & lowering contracts — the compile-time half of the
correctness story.

PR 3/4 built the *runtime* observability (telemetry, forensics); this
package checks the invariants that never reach runtime because they are
properties of the source and of the lowering itself:

  lint       jaxlint — an AST rule engine (rule ids `BMT-Exx`) for the JAX
             failure modes this codebase actually has: PRNG key reuse,
             host sync inside traced scopes, jit cache-miss hazards,
             use-after-donate, broad/bare `except`, wall-clock reads in
             traced code, redundant array conversions. Pure AST — importing
             it never touches jax.
  contracts  Runtime lowering/dispatch contracts: a recompile-budget
             harness (count backend compiles over a warm loop, assert the
             declared budget — normally zero) and a transfer-guard wrapper
             (`jax.transfer_guard("disallow")`) asserting the hot loop
             performs no implicit device<->host transfers.
  lowering   Golden StableHLO fingerprints per (GAR x diagnostics x
             masked-quorum) cell, generalizing `tests/test_diag.py`'s
             byte-identical assertion into a blessed contract
             (`tests/goldens/lowerings.json`, `scripts/bless_lowerings.py`)
             with a CI gate that fails on unexplained lowering drift.
  concurrency  BMT-T lock-discipline rules (RacerD-style thread-role ×
             lock-set analysis over the serve/cluster thread surface):
             unguarded cross-thread writes, inconsistent guards,
             lock-order inversions, blocking calls under locks, leaked
             threads. Registered in `lint.RULES`, so one lint pass runs
             both AST families under one noqa contract.
  schedule   The dynamic twin: a deterministic interleaving harness
             (instrumented Lock/Condition + explicit preemption points,
             replayable schedule strings, exhaustive bounded-preemption
             exploration, deadlock detection) that demonstrates the
             races the T-rules claim and pins the fixed code as
             schedule-clean. `MODEL_COVERAGE` names the files each
             model vouches for — the BMT-L06 covenant input.
  locks      BMT-L whole-program lock discipline: an interprocedural
             lock-order graph (call-graph + lock-set fixpoint across
             modules) with deadlock-cycle detection (L01), transitive
             blocking-under-lock (L02), lock-held callbacks (L03),
             inconsistent order (L04), unlocked lazy init (L05) and
             the mechanical thread-surface covenant (L06); blessed
             hierarchy in `tests/goldens/locks.json`
             (`scripts/bless_locks.py`), runtime cross-check via
             `contracts.record_lock_edges` + `utils/locking.NamedLock`.

CLI: `python -m byzantinemomentum_tpu.analysis <paths...>` lints (E- and
T-families); `--check-lowerings` runs the drift gate; `--check-locks`
runs the BMT-L sweep + golden gate; `--schedule-smoke` runs the
interleaving-harness selfcheck; `--rules` prints the rule table.
Suppressions are per-line `# bmt: noqa[BMT-Exx] <reason>` and the reason
is mandatory (an empty reason is itself a violation, `BMT-E00`).
"""

from byzantinemomentum_tpu.analysis import lint  # noqa: F401 (jax-free)
# Importing registers the BMT-T concurrency rules in lint.RULES (jax-free)
from byzantinemomentum_tpu.analysis import concurrency  # noqa: F401
# ... and the BMT-L lock-discipline rule ids (driver rules: the ids
# validate noqas and fill the --rules table; the checks run in
# locks.build/check, not the per-module pass)
from byzantinemomentum_tpu.analysis import locks  # noqa: F401

__all__ = ["lint", "concurrency", "locks"]
