"""Utility substrate: logging contexts, registries, the `key:value` plugin
argument mini-language, timing scopes and misc helpers.

Capability parity with the reference's `tools/` package (reference
`tools/__init__.py`, `tools/misc.py`), re-designed for a JAX codebase:
no global stdout wrapping, no torch dependencies.
"""

from byzantinemomentum_tpu.utils.logging import (  # noqa: F401
    Context,
    UserException,
    UnavailableException,
    trace,
    info,
    success,
    warning,
    error,
    fatal,
    fatal_unavailable,
)
from byzantinemomentum_tpu.utils.keyval import parse_keyval  # noqa: F401
from byzantinemomentum_tpu.utils.misc import (  # noqa: F401
    import_directory,
    pairwise,
    onetime,
    TimedContext,
    AccumulatedTimedContext,
    deltatime_point,
    deltatime_format,
)
