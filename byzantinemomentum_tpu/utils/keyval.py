"""The `key:value` plugin-argument mini-language.

Every pluggable component (GAR, attack, model, loss, criterion, init) accepts
extra arguments as a list of `key:value` strings with automatic
bool/int/float/str typing — same surface as the reference
(`tools/misc.py:175-235`, applied at `attack.py:244-248`).
"""

__all__ = ["parse_keyval"]


def _auto_type(value):
    low = value.lower()
    if low == "true":
        return True
    if low == "false":
        return False
    try:
        return int(value)
    except ValueError:
        pass
    try:
        return float(value)
    except ValueError:
        pass
    return value


def parse_keyval(entries):
    """Parse a list of `key:value` strings into a dict with auto-typed values.

    Args:
      entries: iterable of strings, each `key:value`; a bare `key` maps to True.
    Returns:
      dict of parsed entries.
    """
    parsed = {}
    if entries is None:
        return parsed
    for entry in entries:
        if ":" in entry:
            key, value = entry.split(":", 1)
            parsed[key] = _auto_type(value)
        else:
            parsed[entry] = True
    return parsed
