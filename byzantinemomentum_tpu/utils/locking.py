"""Named synchronization primitives — the runtime half of the BMT-L
lock-discipline contract (`analysis/locks.py`).

`NamedLock("router.ring")` behaves exactly like `threading.Lock()` but
carries a stable, human-chosen name, so

  * static BMT-L reports say `router.ring -> router.membership`, not
    `<anonymous Lock at router.py:162>`;
  * the runtime acquisition log (`install_recorder` below, surfaced
    through `analysis/contracts.record_lock_edges`) emits the SAME
    names the static lock-order graph uses, which is what makes the
    runtime-subset-of-static cross-check a set comparison instead of a
    heuristic join.

Edge recording: while a recorder is installed, every thread keeps a
thread-local stack of the named locks it currently holds; acquiring a
named primitive while others are held emits one `(held, taken)` pair
per held lock to the recorder. With NO recorder installed the wrapper
does no bookkeeping at all — each acquisition pays one module-global
None check on top of the raw lock, which is what lets the serve hot
path (the pre-bound metrics counters take one of these per `inc`) use
named locks unconditionally. Consequences of the lazy stance:

  * `held_locks()` only reflects acquisitions made while a recorder
    was installed — install the recorder BEFORE the traffic window;
  * a lock already held when the recorder installs is invisible until
    its next acquisition (each primitive tracks whether its CURRENT
    hold was noted, so install/uninstall mid-hold never corrupts the
    stack — an un-noted hold simply never pops).

The module is stdlib-only and imports nothing from the package: `obs`,
`serve` and `cluster` all sit above it.
"""

import threading

__all__ = ["NamedLock", "NamedCondition", "install_recorder",
           "uninstall_recorder", "held_locks"]


_held = threading.local()
_recorder = None            # callable((held_name, taken_name)) or None
_recorder_lock = threading.Lock()  # bmt: noqa[BMT-L06] the recorder latch guards one module global; the wrapper itself is pinned by tests/test_locks.py's runtime-edge tests


def _stack():
    try:
        return _held.stack
    except AttributeError:
        _held.stack = []
        return _held.stack


def _note_acquired(name):
    stack = _stack()
    rec = _recorder
    if rec is not None:
        for held in stack:
            try:
                rec((held, name))
            except Exception:  # bmt: noqa[BMT-E05] a broken observer must not poison every lock acquisition in the process
                pass
    stack.append(name)


def _note_released(name):
    stack = _stack()
    # Remove the LAST occurrence: releases normally pop in LIFO order,
    # but out-of-order release is legal for bare acquire()/release()
    for index in range(len(stack) - 1, -1, -1):
        if stack[index] == name:
            del stack[index]
            return


def install_recorder(callback):
    """Install `callback((held, taken))` as the process-wide acquisition
    observer; returns the previous recorder (restore it via
    `uninstall_recorder`). One recorder at a time — last install wins,
    which is all the selfcheck/test windows need."""
    global _recorder
    with _recorder_lock:
        previous = _recorder
        _recorder = callback
    return previous


def uninstall_recorder(previous=None):
    """Remove the acquisition observer (restoring `previous`)."""
    global _recorder
    with _recorder_lock:
        _recorder = previous


def held_locks():
    """Names of the locks the CALLING thread currently holds, innermost
    last (diagnostics; the recorder sees the cross-thread picture).
    Only populated while a recorder is installed — see the module
    note."""
    return tuple(_stack())


class NamedLock:
    """`threading.Lock` with a name and acquisition-edge recording.

    `_noted` tracks whether the CURRENT hold was pushed onto the
    thread-local stack: it is only read/written by the holder (the lock
    is non-reentrant), so a recorder installed or removed mid-hold
    cannot unbalance the bookkeeping."""

    __slots__ = ("name", "_lock", "_noted")

    def __init__(self, name):
        self.name = str(name)
        self._lock = threading.Lock()
        self._noted = False

    def acquire(self, blocking=True, timeout=-1):
        ok = self._lock.acquire(blocking, timeout)
        if ok and _recorder is not None:
            self._noted = True
            _note_acquired(self.name)
        return ok

    def release(self):
        if self._noted:
            self._noted = False
            _note_released(self.name)
        self._lock.release()

    def locked(self):
        return self._lock.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self):
        return f"NamedLock({self.name!r})"


class NamedCondition:
    """`threading.Condition` with a name and acquisition-edge recording.

    `wait()` pops the name for the duration of the wait (the underlying
    lock really is released) and re-records the reacquisition on wake —
    so a consumer parked in `wait()` never appears to hold the
    condition in the runtime edge log. `_noted` is only touched while
    the underlying lock is held (before the release inside `wait`, after
    the reacquire on wake), so waiters cannot race it."""

    __slots__ = ("name", "_cond", "_noted")

    def __init__(self, name, lock=None):
        self.name = str(name)
        self._cond = threading.Condition(lock)
        self._noted = False

    def acquire(self, *args, **kwargs):
        ok = self._cond.acquire(*args, **kwargs)
        if ok and _recorder is not None:
            self._noted = True
            _note_acquired(self.name)
        return ok

    def release(self):
        if self._noted:
            self._noted = False
            _note_released(self.name)
        self._cond.release()

    def wait(self, timeout=None):
        if self._noted:
            self._noted = False
            _note_released(self.name)
        try:
            return self._cond.wait(timeout)
        finally:
            if _recorder is not None:
                self._noted = True
                _note_acquired(self.name)

    def wait_for(self, predicate, timeout=None):
        if self._noted:
            self._noted = False
            _note_released(self.name)
        try:
            return self._cond.wait_for(predicate, timeout)
        finally:
            if _recorder is not None:
                self._noted = True
                _note_acquired(self.name)

    def notify(self, n=1):
        self._cond.notify(n)

    def notify_all(self):
        self._cond.notify_all()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self):
        return f"NamedCondition({self.name!r})"
