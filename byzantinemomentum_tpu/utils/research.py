"""Off-the-main-path research helpers (reference `tools/pytorch.py:199-294`:
`regression`, `WeightedMSELoss`, `pnm`) — jax-idiomatic equivalents.

Like their reference counterparts, these support ad-hoc analysis scripts and
are not used by the training pipeline.
"""

import numpy as np

__all__ = ["regression", "weighted_mse", "pnm"]


def regression(fn, params0, x, y, *, weights=None, steps=1000, lr=1e-2):
    """Fit `fn(params, x) -> y` by (weighted) least squares with Adam on
    `jax.grad` (reference `tools/pytorch.py:199-244` fitted with torch).

    Args:
      fn: traceable model `(params pytree, f32[n]) -> f32[n]`.
      params0: initial parameter pytree.
      x, y: data arrays.
      weights: optional per-point weights (reference `WeightedMSELoss`,
        `tools/pytorch.py:249-266`).
      steps, lr: optimization budget.
    Returns:
      (fitted params pytree, final loss).
    """
    import jax
    import jax.numpy as jnp
    import optax

    x = jnp.asarray(x, jnp.float32)
    y = jnp.asarray(y, jnp.float32)
    w = jnp.ones_like(y) if weights is None else jnp.asarray(weights, jnp.float32)

    def loss_fn(params):
        return weighted_mse(fn(params, x), y, w)

    tx = optax.adam(lr)
    opt_state = tx.init(params0)

    @jax.jit
    def step(params, opt_state):
        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state)
        return optax.apply_updates(params, updates), opt_state, loss

    params = params0
    for _ in range(steps):
        params, opt_state, loss = step(params, opt_state)
    return params, float(loss)


def weighted_mse(pred, target, weights):
    """Weighted mean squared error (reference `WeightedMSELoss`,
    `tools/pytorch.py:249-266`)."""
    import jax.numpy as jnp
    return jnp.sum(weights * (pred - target) ** 2) / jnp.sum(weights)


def pnm(path, array):
    """Dump a 2-D array as a portable anymap: PBM for bool, PGM for
    uint8/float in [0, 1] (reference `tools/pytorch.py:271-294`)."""
    array = np.asarray(array)
    if array.ndim != 2:
        raise ValueError(f"Expected a 2-D array, got shape {array.shape}")
    with open(path, "wb") as fd:
        if array.dtype == bool:
            fd.write(b"P1\n%d %d\n" % (array.shape[1], array.shape[0]))
            for row in array:
                fd.write(b" ".join(b"1" if v else b"0" for v in row) + b"\n")
        else:
            if array.dtype != np.uint8:
                array = np.clip(array * 255.0, 0, 255).astype(np.uint8)
            fd.write(b"P2\n%d %d\n255\n" % (array.shape[1], array.shape[0]))
            for row in array:
                fd.write(b" ".join(b"%d" % v for v in row) + b"\n")
    return path
