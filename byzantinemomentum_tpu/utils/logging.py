"""Colored, nested, thread-aware contextual logging.

Same capability as the reference's `tools.Context` / `tools.trace..fatal`
helpers (reference `tools/__init__.py:34-216`), without globally wrapping
`sys.stdout`/`sys.stderr`: log lines are emitted explicitly, which plays
nicer with JAX's own logging and with pytest capture.
"""

import sys
import threading

__all__ = [
    "Context",
    "UserException",
    "UnavailableException",
    "trace",
    "info",
    "success",
    "warning",
    "error",
    "fatal",
    "fatal_unavailable",
]


class UserException(RuntimeError):
    """An error caused by invalid user input, printed without a traceback."""


class UnavailableException(UserException):
    """An unknown name was requested from a registry."""

    def __init__(self, registry, name, what="entry"):
        avail = ", ".join(repr(k) for k in sorted(registry))
        super().__init__(f"Unknown {what} {name!r}, expected one of: {avail}")


_COLORS = {
    "trace": "\033[90m",
    "info": "\033[0m",
    "success": "\033[32m",
    "warning": "\033[33m",
    "error": "\033[31m",
    "header": "\033[1;34m",
}
_RESET = "\033[0m"

_tls = threading.local()


def _stack():
    if not hasattr(_tls, "stack"):
        _tls.stack = []
    return _tls.stack


class Context:
    """Nested `[name]` logging scope, rendered as a prefix on emitted lines."""

    def __init__(self, name, level="info"):
        self.name = name
        self.level = level

    def __enter__(self):
        _stack().append(self.name)
        return self

    def __exit__(self, *exc):
        _stack().pop()
        return False


def _emit(level, *args, file=None):
    file = file if file is not None else (sys.stderr if level in ("warning", "error") else sys.stdout)
    use_color = hasattr(file, "isatty") and file.isatty()
    prefix = "".join(f"[{name}] " for name in _stack())
    thread = threading.current_thread()
    if thread is not threading.main_thread():
        prefix = f"[{thread.name}] " + prefix
    text = " ".join(str(a) for a in args)
    if use_color:
        print(f"{_COLORS.get(level, '')}{prefix}{text}{_RESET}", file=file, flush=True)
    else:
        print(f"{prefix}{text}", file=file, flush=True)


def trace(*args):
    _emit("trace", *args)


def info(*args):
    _emit("info", *args)


def success(*args):
    _emit("success", *args)


def warning(*args):
    _emit("warning", *args)


def error(*args):
    _emit("error", *args)


def fatal(*args):
    """Print an error and raise a UserException (reference exits the process;
    raising keeps the framework usable as a library)."""
    _emit("error", *args)
    raise UserException(" ".join(str(a) for a in args))


def fatal_unavailable(registry, name, what="entry"):
    """Raise for an unknown registry name, listing the valid ones
    (reference `tools/misc.py:35-75`)."""
    raise UnavailableException(registry, name, what=what)
