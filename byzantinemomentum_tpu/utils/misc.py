"""Misc helpers: plugin directory auto-import, pair generation, one-time
latches and wall-clock timing scopes.

Parity targets in the reference: `tools/__init__.py:251-305`
(`import_directory`), `tools/misc.py:259-343` (`onetime`, `TimedContext`),
`tools/misc.py:519-529` (`pairwise`), `tools/pytorch.py:130-194`
(`AccumulatedTimedContext`).
"""

import importlib
import pathlib
import threading
import time

from byzantinemomentum_tpu.utils import logging as _log

__all__ = [
    "import_directory",
    "pairwise",
    "onetime",
    "TimedContext",
    "AccumulatedTimedContext",
    "deltatime_point",
    "deltatime_format",
    "interactive",
    "get_loaded_dependencies",
]


def interactive(local=None):
    """In-process REPL, resumed with Ctrl-D (reference `tools/misc.py:348-412`;
    wired to `--user-input-delta` like the reference's `attack.py:733-734`)."""
    import code
    code.interact(banner="Interactive prompt; Ctrl-D to resume",
                  local=local or {})


def get_loaded_dependencies():
    """List the loaded third-party modules with their versions
    (reference `tools/misc.py:417-463` — used there to generate the README's
    dependency table)."""
    import sys
    out = {}
    for name, module in sorted(sys.modules.items()):
        if "." in name or name.startswith("_"):
            continue
        version = getattr(module, "__version__", None)
        if version is not None:
            out[name] = str(version)
    return out


def import_directory(package, path):
    """Import every python module in a directory, making plugin modules
    self-register (the loader behind the GAR/attack/model/dataset registries,
    reference `tools/__init__.py:280-305`).

    Args:
      package: fully qualified package name the modules belong to.
      path: directory to scan (str or Path).
    """
    path = pathlib.Path(path)
    for child in sorted(path.iterdir()):
        if child.name.startswith("_") or not child.name.endswith(".py"):
            continue
        importlib.import_module(f"{package}.{child.stem}")


def pairwise(data):
    """Generate the pairs (data[i], data[j]) with i < j
    (reference `tools/misc.py:519-529`)."""
    n = len(data)
    for i in range(n - 1):
        for j in range(i + 1, n):
            yield (data[i], data[j])


def onetime(callback):
    """Thread-safe one-time latch: returns (trigger, is_triggered) where
    `trigger()` runs `callback` at most once (reference `tools/misc.py:259-302`
    — used for graceful SIGINT/SIGTERM exit)."""
    lock = threading.Lock()  # bmt: noqa[BMT-L06] one-shot latch (single lock, no nesting) for signal handlers — nothing to order
    state = {"done": False}

    def trigger(*args, **kwargs):
        with lock:
            if state["done"]:
                return
            state["done"] = True
        if callback is not None:
            callback(*args, **kwargs)

    def is_triggered():
        with lock:
            return state["done"]

    return trigger, is_triggered


def deltatime_point():
    """Monotonic time point for interval measurement."""
    return time.monotonic()


def deltatime_format(seconds):
    """Format a duration in seconds as `H:MM:SS.mmm`."""
    sign = "-" if seconds < 0 else ""
    seconds = abs(seconds)
    hours, rem = divmod(seconds, 3600)
    minutes, secs = divmod(rem, 60)
    return f"{sign}{int(hours)}:{int(minutes):02d}:{secs:06.3f}"


class TimedContext:
    """Wall-clock scope printing elapsed time on exit
    (reference `tools/misc.py:307-343`)."""

    def __init__(self, label="elapsed"):
        self._label = label

    def __enter__(self):
        self._start = time.monotonic()
        return self

    def __exit__(self, *exc):
        _log.trace(f"{self._label}: {deltatime_format(time.monotonic() - self._start)}")
        return False


class AccumulatedTimedContext:
    """Re-enterable timing scope accumulating total elapsed time across
    entries; `sync` calls a supplied barrier (e.g. `jax.block_until_ready`
    on a sentinel) before each start/stop for honest device timing
    (reference `tools/pytorch.py:130-194` used `torch.cuda.synchronize`)."""

    def __init__(self, label="total", sync=None):
        self._label = label
        self._sync = sync
        self._total = 0.0

    def __enter__(self):
        if self._sync is not None:
            self._sync()
        self._start = time.monotonic()
        return self

    def __exit__(self, *exc):
        if self._sync is not None:
            self._sync()
        self._total += time.monotonic() - self._start
        return False

    @property
    def total(self):
        return self._total

    def __str__(self):
        return f"{self._label}: {deltatime_format(self._total)}"
