"""Experiment scheduler: a thread pool dispatching driver subprocesses over
devices (reference `tools/jobs.py:27-248`).

Capability parity:
* one worker thread per (device × supercharge) slot
  (reference `jobs.py:169-191`);
* jobs are (name, seed, command) triples run as subprocesses with captured
  stdout/stderr written next to the results (reference `jobs.py:111-146`);
* idempotency — a job whose final result directory already exists is
  skipped, so interrupted grids resume for free (reference `jobs.py:126-129`);
* failure containment — a failed run's pending directory is renamed
  `<name>.failed` and preserved for inspection (reference `jobs.py:140-144`);
* per-seed expansion with the reference's default seeds 1..5
  (reference `jobs.py:169`).

On TPU, "devices" are whole accelerator slices/processes rather than the
reference's per-GPU `--device cuda:N`: each slot exports its device string
through the `BMT_JOB_DEVICE` environment variable and passes it to the
driver's `--device` flag.
"""

import pathlib
import queue
import subprocess
import threading

from byzantinemomentum_tpu.utils import logging as _log

__all__ = ["Jobs", "dict_to_cmdlist"]

DEFAULT_SEEDS = (1, 2, 3, 4, 5)


def dict_to_cmdlist(options):
    """Flatten `{flag: value}` into a command-line fragment
    (reference `tools/jobs.py:27-46`): None skips the flag, True emits the
    bare flag, lists emit one flag with several values."""
    cmd = []
    for key, value in options.items():
        if value is None or value is False:
            continue
        cmd.append(f"--{key.replace('_', '-')}")
        if value is True:
            continue
        if isinstance(value, (list, tuple)):
            cmd.extend(str(v) for v in value)
        else:
            cmd.append(str(value))
    return cmd


class Jobs:
    """Thread-pool scheduler of driver subprocesses."""

    def __init__(self, results_dir, devices=("auto",), supercharge=1,
                 seeds=DEFAULT_SEEDS):
        """Args mirror the reference's (`tools/jobs.py:107-124`,
        `--supercharge` from `reproduce.py:62-65`): one worker per device
        repeated `supercharge` times."""
        if supercharge < 1:
            raise ValueError(f"Expected a positive supercharge, got {supercharge}")
        self.results_dir = pathlib.Path(results_dir)
        self.results_dir.mkdir(parents=True, exist_ok=True)
        self.seeds = tuple(seeds)
        self._queue = queue.Queue()
        self._threads = []
        self._started = False
        self._devices = tuple(devices) * supercharge

    def submit(self, name, command):
        """Queue one experiment under `name`; it expands into one run per
        seed, each appending `--seed <s> --result-directory <dir>`
        (reference `tools/jobs.py:193-217`)."""
        for seed in self.seeds:
            self._queue.put((f"{name}-{seed}", seed, list(command)))

    def _run_one(self, slot_device, run_name, seed, command):
        final_dir = self.results_dir / run_name
        if final_dir.exists():
            _log.trace(f"{run_name}: already done, skipping")
            return
        pending = self.results_dir / f"{run_name}.pending"
        if pending.exists():
            # Rotate a stale pending dir out of the way
            # (reference `tools/jobs.py:27-46` version rotation)
            version = 0
            while (self.results_dir / f"{run_name}.pending.{version}").exists():
                version += 1
            pending.rename(self.results_dir / f"{run_name}.pending.{version}")
        pending.mkdir(parents=True)
        cmd = command + ["--seed", str(seed),
                         "--device", slot_device,
                         "--result-directory", str(pending)]
        _log.info(f"{run_name}: starting on {slot_device!r}")
        with (pending / "stdout.log").open("wb") as out, \
                (pending / "stderr.log").open("wb") as err:
            result = subprocess.run(cmd, stdout=out, stderr=err,
                                    env=self._env(slot_device))
        if result.returncode == 0:
            pending.rename(final_dir)
            _log.success(f"{run_name}: done")
        else:
            failed = self.results_dir / f"{run_name}.failed"
            if failed.exists():
                # Rotate the previous failure out of the way (os.rename
                # cannot replace a non-empty directory)
                version = 0
                while (self.results_dir
                       / f"{run_name}.failed.{version}").exists():
                    version += 1
                failed.rename(self.results_dir / f"{run_name}.failed.{version}")
            pending.rename(failed)
            _log.error(f"{run_name}: failed with code {result.returncode} "
                       f"(logs kept in {run_name}.failed)")

    @staticmethod
    def _env(device):
        import os
        env = dict(os.environ)
        env["BMT_JOB_DEVICE"] = device
        return env

    def _worker(self, slot_device):
        while True:
            try:
                run_name, seed, command = self._queue.get_nowait()
            except queue.Empty:
                return
            try:
                self._run_one(slot_device, run_name, seed, command)
            except Exception as err:
                _log.error(f"{run_name}: scheduler error: {err}")
            finally:
                self._queue.task_done()

    def start(self):
        if self._started:
            return
        self._started = True
        for i, device in enumerate(self._devices):
            t = threading.Thread(target=self._worker, args=(device,),
                                 name=f"jobs-{device}-{i}", daemon=True)
            t.start()
            self._threads.append(t)

    def wait(self, exit_is_requested=None):
        """Run all queued jobs to completion (reference `jobs.py:219-239`);
        `exit_is_requested()` polls an abort latch."""
        self.start()
        for t in self._threads:
            while t.is_alive():
                t.join(timeout=0.5)
                if exit_is_requested is not None and exit_is_requested():
                    return
