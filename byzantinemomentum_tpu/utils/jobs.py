"""Experiment supervisor: a thread pool dispatching driver subprocesses over
devices (reference `tools/jobs.py:27-248`), hardened for preemptible
machines.

Capability parity:
* one worker thread per (device × supercharge) slot
  (reference `jobs.py:169-191`);
* jobs are (name, seed, command) triples run as subprocesses with captured
  stdout/stderr written next to the results (reference `jobs.py:111-146`);
* idempotency — a job whose final result directory already exists is
  skipped, so interrupted grids resume for free (reference `jobs.py:126-129`);
* failure containment — a failed run's pending directory is renamed
  `<name>.failed` and preserved for inspection (reference `jobs.py:140-144`).
* per-seed expansion with the reference's default seeds 1..5
  (reference `jobs.py:169`).

Beyond the reference (PR 2 — the reference gives a crashed run exactly one
attempt before parking it in `.failed` forever):

* **retry with backoff** — a failed attempt is retried in-place up to
  `max_retries` times with exponential backoff, in the SAME pending
  directory, so the driver's `--auto-resume` (appended to every dispatched
  command via `resume_flag`) continues from the attempt's newest valid
  checkpoint instead of cold-starting;
* **adoption** — a stale `.pending` (a previous scheduler was killed) or a
  previous `.failed` directory holding a valid checkpoint is adopted as the
  new pending directory and resumed, rather than rotated away/ignored;
* **heartbeat watchdog** — with `heartbeat_timeout`, a subprocess whose
  progress signal stops advancing for that long is SIGKILLed and retried
  (hung collective, wedged remote device, ...). The signal is the driver's
  `heartbeat.json` (PR 3, `obs/heartbeat.py` — written atomically with the
  step and wall time, so the kill decision is signal-based); runs without
  a heartbeat yet (legacy drivers, cold starts before the first telemetry
  write) fall back to study-CSV mtime, and the watchdog logs which signal
  it is tracking;
* the `.pending`/`.failed` version rotation is race-free under concurrent
  worker threads (the rename itself is the existence test, serialized by a
  per-results-dir lock).

On TPU, "devices" are whole accelerator slices/processes rather than the
reference's per-GPU `--device cuda:N`: each slot exports its device string
through the `BMT_JOB_DEVICE` environment variable and passes it to the
driver's `--device` flag.

Fleet supervision (PR 12): the multi-host cluster launcher
(`byzantinemomentum_tpu/cluster/launcher.py`) aggregates its hosts'
per-host heartbeats into the SAME top-level `heartbeat.json` a training
run writes, so `Jobs(seeds=(None,), heartbeat_timeout=...)` — the
seedless service-job form above — supervises a whole N-process fleet
with zero changes here: a wedged launcher stalls the aggregated
heartbeat, the watchdog SIGKILLs it (the hosts die with it through
their launcher-held stdin pipes), and the retry's `--auto-resume`
relaunches the fleet from the off-slice checkpoint mirror
(`tests/test_cluster.py::test_jobs_supervises_cluster_launcher_service_job`).
The serve fleet launcher (PR 16, `serve/fleet/launcher.py`) follows the
same aggregated-heartbeat contract — per-shard serve heartbeats sum into
one top-level `heartbeat.json` whose `step` is total requests served —
so the identical seedless form supervises an N-shard aggregation fleet
too: shard restarts are the launcher's job, launcher death is this
watchdog's.
"""

import os
import pathlib
import queue
import subprocess
import threading
import time

from byzantinemomentum_tpu.utils import logging as _log
# Host-only (no jax import): safe in supervisor threads
from byzantinemomentum_tpu.obs.heartbeat import read_heartbeat as _read_heartbeat
from byzantinemomentum_tpu.utils.locking import NamedLock

__all__ = ["Jobs", "dict_to_cmdlist"]

DEFAULT_SEEDS = (1, 2, 3, 4, 5)


def dict_to_cmdlist(options):
    """Flatten `{flag: value}` into a command-line fragment
    (reference `tools/jobs.py:27-46`): None skips the flag, True emits the
    bare flag, lists emit one flag with several values."""
    cmd = []
    for key, value in options.items():
        if value is None or value is False:
            continue
        cmd.append(f"--{key.replace('_', '-')}")
        if value is True:
            continue
        if isinstance(value, (list, tuple)):
            cmd.extend(str(v) for v in value)
        else:
            cmd.append(str(value))
    return cmd


class Jobs:
    """Thread-pool supervisor of driver subprocesses."""

    def __init__(self, results_dir, devices=("auto",), supercharge=1,
                 seeds=DEFAULT_SEEDS, max_retries=1, retry_backoff=1.0,
                 heartbeat_timeout=None, resume_flag="--auto-resume"):
        """Args mirror the reference's (`tools/jobs.py:107-124`,
        `--supercharge` from `reproduce.py:62-65`): one worker per device
        repeated `supercharge` times.

        Supervisor knobs:
          max_retries: extra attempts a failing run gets (0 = the
            reference's single-shot behavior); attempt k waits
            `retry_backoff * 2**(k-1)` seconds first.
          heartbeat_timeout: seconds without the run's study CSV advancing
            before the subprocess is killed and the attempt counted failed
            (None disables the watchdog).
          resume_flag: appended to every dispatched command so retried or
            adopted runs continue from their newest valid checkpoint (the
            driver's `--auto-resume`); None disables both the flag and the
            checkpoint-based adoption of stale directories.
        """
        if supercharge < 1:
            raise ValueError(f"Expected a positive supercharge, got {supercharge}")
        if max_retries < 0:
            raise ValueError(f"Expected a non-negative retry count, got {max_retries}")
        if heartbeat_timeout is not None and heartbeat_timeout <= 0:
            raise ValueError(f"Expected a positive heartbeat timeout, got "
                             f"{heartbeat_timeout}")
        self.results_dir = pathlib.Path(results_dir)
        self.results_dir.mkdir(parents=True, exist_ok=True)
        self.seeds = tuple(seeds)
        self.max_retries = max_retries
        self.retry_backoff = retry_backoff
        self.heartbeat_timeout = heartbeat_timeout
        self.resume_flag = resume_flag
        self._queue = queue.Queue()
        self._threads = []
        self._started = False
        self._rotate_lock = NamedLock("jobs.rotate")
        self._devices = tuple(devices) * supercharge

    def submit(self, name, command):
        """Queue one experiment under `name`; it expands into one run per
        seed, each appending `--seed <s> --result-directory <dir>`
        (reference `tools/jobs.py:193-217`).

        A seed of None queues ONE seedless run under the bare `name` (no
        `--seed` flag, no name suffix) — the service-job form: long-lived
        processes like the aggregation server
        (`python -m byzantinemomentum_tpu.serve --result-directory ...`)
        write the same `heartbeat.json` a training run does, so
        `seeds=(None,)` plus `heartbeat_timeout` gives them the exact
        watchdog/kill/retry supervision runs get."""
        for seed in self.seeds:
            run_name = name if seed is None else f"{name}-{seed}"
            self._queue.put((run_name, seed, list(command)))

    # ------------------------------------------------------------------ #
    # Crash-recovery helpers

    @staticmethod
    def _has_valid_checkpoint(directory):
        """Whether `directory` holds a checkpoint a retry can resume from
        (never raises: the supervisor must not die on a mangled dir)."""
        try:
            from byzantinemomentum_tpu import checkpoint
            return checkpoint.find_latest_valid(directory) is not None
        except Exception:  # bmt: noqa[BMT-E05] the supervisor must not die on a mangled run dir (or a broken checkpoint import chain); no checkpoint == cold retry
            return False

    def _rotate_away(self, path):
        """Version-rotate `path` out of the way (`<name>.0`, `<name>.1`, …)
        race-free under concurrent workers: the rename itself is the
        existence test — renaming onto a non-empty directory fails — and
        the scan-and-rename is serialized by the per-results-dir lock
        (the previous exists-then-rename could race two threads onto the
        same version)."""
        with self._rotate_lock:
            version = 0
            while True:
                target = path.with_name(f"{path.name}.{version}")
                try:
                    path.rename(target)
                    return target
                except OSError:
                    if not target.exists():
                        raise
                    version += 1

    def _prepare_pending(self, run_name):
        """The pending directory one run's attempts all share — adopting a
        resumable previous attempt (stale `.pending` from a killed
        scheduler, or `.failed` from an exhausted one) when possible."""
        pending = self.results_dir / f"{run_name}.pending"
        failed = self.results_dir / f"{run_name}.failed"
        if pending.exists():
            if self.resume_flag and self._has_valid_checkpoint(pending):
                _log.info(f"{run_name}: adopting stale pending directory "
                          f"(valid checkpoint found; resuming)")
                return pending
            # Rotate a non-resumable stale pending dir out of the way
            self._rotate_away(pending)
        elif (failed.exists() and self.resume_flag
                and self._has_valid_checkpoint(failed)):
            failed.rename(pending)
            _log.info(f"{run_name}: adopting previous failed attempt "
                      f"(valid checkpoint found; resuming)")
            return pending
        pending.mkdir(parents=True)
        return pending

    # ------------------------------------------------------------------ #
    # One run = up to 1 + max_retries attempts over one pending directory

    def _run_one(self, slot_device, run_name, seed, command):
        final_dir = self.results_dir / run_name
        if final_dir.exists():
            _log.trace(f"{run_name}: already done, skipping")
            return
        pending = self._prepare_pending(run_name)
        cmd = command + (["--seed", str(seed)] if seed is not None else [])
        cmd += ["--device", slot_device,
                "--result-directory", str(pending)]
        if self.resume_flag and self.resume_flag not in cmd:
            # Retries/adoptions resume from the pending dir's newest valid
            # checkpoint; on a fresh dir the flag is a no-op cold start
            cmd = cmd + [self.resume_flag]
        _log.info(f"{run_name}: starting on {slot_device!r}")
        for attempt in range(self.max_retries + 1):
            if attempt:
                delay = self.retry_backoff * (2 ** (attempt - 1))
                resumes = self._has_valid_checkpoint(pending)
                _log.info(f"{run_name}: retry {attempt}/{self.max_retries} "
                          f"in {delay:.1f}s"
                          + (" (resuming from checkpoint)" if resumes
                             else " (cold start)"))
                time.sleep(delay)
            returncode = self._spawn(run_name, pending, cmd, slot_device)
            if returncode == 0:
                pending.rename(final_dir)
                _log.success(f"{run_name}: done")
                return
            _log.error(f"{run_name}: attempt {attempt + 1} failed with "
                       f"code {returncode}")
        failed = self.results_dir / f"{run_name}.failed"
        if failed.exists():
            # Rotate the previous failure out of the way (os.rename
            # cannot replace a non-empty directory)
            self._rotate_away(failed)
        pending.rename(failed)
        _log.error(f"{run_name}: failed after {self.max_retries + 1} "
                   f"attempt(s) (logs kept in {run_name}.failed)")

    def _spawn(self, run_name, pending, cmd, slot_device):
        """Launch one attempt; with a heartbeat timeout, watchdog the run's
        progress signal and SIGKILL the subprocess when it stalls. Logs are
        opened in append mode so every attempt's output is preserved."""
        with (pending / "stdout.log").open("ab") as out, \
                (pending / "stderr.log").open("ab") as err:
            proc = subprocess.Popen(cmd, stdout=out, stderr=err,
                                    env=self._env(slot_device))
            if self.heartbeat_timeout is None:
                return proc.wait()
            poll = self._poll_interval()
            last_beat = time.monotonic()
            last_source = None
            last_sig = self._progress_signature(pending)
            while True:
                try:
                    return proc.wait(timeout=poll)
                except subprocess.TimeoutExpired:
                    pass
                sig = self._progress_signature(pending)
                source = sig[0] if sig is not None else None
                if source is not None and source != last_source:
                    # Which liveness signal rules: the driver's atomic
                    # heartbeat.json when present, study-CSV mtime for
                    # legacy/cold-start runs that have none yet
                    _log.info(f"{run_name}: watchdog tracking "
                              + ("heartbeat.json" if source == "heartbeat"
                                 else "study-CSV mtime (no heartbeat yet)"))
                    last_source = source
                now = time.monotonic()
                if sig != last_sig:
                    last_sig, last_beat = sig, now
                elif now - last_beat > self.heartbeat_timeout:
                    _log.error(f"{run_name}: heartbeat lost "
                               f"({'heartbeat.json' if source == 'heartbeat' else 'study CSV'} "
                               f"stalled > {self.heartbeat_timeout}s); "
                               f"killing the subprocess")
                    proc.kill()
                    return proc.wait()

    def _poll_interval(self):
        """Seconds between watchdog polls: a quarter of the timeout so a
        stall is caught promptly, clamped to [0.05, 0.5] — the FLOOR is
        applied last, so a tiny `heartbeat_timeout` (< 0.2) polls at 20 Hz
        instead of busy-spinning `proc.wait` at `timeout/4` granularity."""
        return max(0.05, min(0.5, self.heartbeat_timeout / 4.0))

    @staticmethod
    def _progress_signature(pending):
        """Progress signature of one run attempt, tagged with its source:
        `("heartbeat", step, updated)` from the run's atomic
        `heartbeat.json` when one exists (the driver refreshes it every
        telemetry sample — a signal, not an inference), else
        `("study", size, mtime)` from the study CSV, else None (process
        start then counts as the last beat)."""
        heartbeat = _read_heartbeat(pending)
        if heartbeat is not None:
            return ("heartbeat", heartbeat.get("step"),
                    heartbeat.get("updated"))
        try:
            stat = (pending / "study").stat()
            return ("study", stat.st_size, stat.st_mtime_ns)
        except OSError:
            return None

    @staticmethod
    def _env(device):
        env = dict(os.environ)
        env["BMT_JOB_DEVICE"] = device
        return env

    def _worker(self, slot_device):
        while True:
            try:
                run_name, seed, command = self._queue.get_nowait()
            except queue.Empty:
                return
            try:
                self._run_one(slot_device, run_name, seed, command)
            except Exception as err:  # bmt: noqa[BMT-E05] one run's scheduler fault must not kill the worker thread draining the queue
                _log.error(f"{run_name}: scheduler error: {err}")
            finally:
                self._queue.task_done()

    def start(self):
        if self._started:
            return
        self._started = True
        for i, device in enumerate(self._devices):
            t = threading.Thread(target=self._worker, args=(device,),
                                 name=f"jobs-{device}-{i}", daemon=True)
            t.start()
            self._threads.append(t)

    def wait(self, exit_is_requested=None):
        """Run all queued jobs to completion (reference `jobs.py:219-239`);
        `exit_is_requested()` polls an abort latch."""
        self.start()
        for t in self._threads:
            while t.is_alive():
                t.join(timeout=0.5)
                if exit_is_requested is not None and exit_is_requested():
                    return
