"""The experiment driver — flag-for-flag parity with the reference's
`attack.py` (reference `attack.py:51-240` for the CLI surface).

Division of labor (redesigned for TPU): the whole per-step computation is one
jitted XLA program (`engine/step.py`); this driver only parses flags, samples
host batches, runs milestones (eval / checkpoint / user input), formats the
`eval` and 24-column `study` CSVs (byte-compatible with the reference's
`study.Session` parser, reference `study.py:216-229`) and handles graceful
SIGINT/SIGTERM (reference `attack.py:41-45`).

Crash recovery (PR 2, for preemptible slices): `--auto-resume` restarts
from the result directory's newest VALID checkpoint (atomic writes +
integrity footers, `checkpoint.py`) and truncates/appends the CSVs so the
concatenated output of a killed + resumed run is bit-identical to an
uninterrupted one (`tests/test_chaos.py`); `--rollback-budget` adds an
in-loop divergence watchdog that restores the last good checkpoint when
the training state goes non-finite.

Telemetry (PR 3, `byzantinemomentum_tpu/obs/`): every run with a result
directory records a machine-readable system timeline — `telemetry.jsonl`
(spans, events, counters, gauges) plus an atomically-replaced
`heartbeat.json` the `Jobs` supervisor's watchdog consumes. Sampling is
interval-based (`--telemetry-interval`) so the depth-2 dispatch pipeline
stays intact between samples; SIGUSR1 captures an on-demand one-chunk
`jax.profiler` window on a live run.
"""

import argparse
import code
import json
import math
import os
import pathlib
import signal
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from byzantinemomentum_tpu import attacks as attacks_mod
from byzantinemomentum_tpu import checkpoint as checkpoint_mod
from byzantinemomentum_tpu import data as data_mod
from byzantinemomentum_tpu import losses as losses_mod
from byzantinemomentum_tpu import models as models_mod
from byzantinemomentum_tpu import obs as obs_mod
from byzantinemomentum_tpu import ops as ops_mod
from byzantinemomentum_tpu import utils
from byzantinemomentum_tpu.engine import (
    EngineConfig, FAULT_COLUMNS, FORENSIC_COLUMNS, HEALTH_COLUMNS,
    RECOVERY_COLUMNS, STUDY_COLUMNS, build_engine)
from byzantinemomentum_tpu.models.core import apply_named_init

__all__ = ["process_commandline", "main"]


def process_commandline(argv=None):
    """Parse the command line (reference `attack.py:51-240`; same flags)."""
    parser = argparse.ArgumentParser(
        prog="attack", formatter_class=argparse.RawTextHelpFormatter)
    add = parser.add_argument
    add("--seed", type=int, default=-1,
        help="Fixed seed for reproducibility, negative for random seed")
    add("--device", type=str, default="auto",
        help="JAX device/platform to run on ('auto', 'tpu', 'cpu', ...)")
    add("--device-gar", type=str, default="same",
        help="Device/platform on which to run the defense phase (attack + "
             "GAR), 'same' to fuse it into the training program (the fast "
             "default). E.g. 'cpu': the honest gradients hop to the CPU "
             "every step and the defense gradient hops back — the "
             "reference's heterogeneous placement")
    add("--dtype", type=str, default="float32",
        help="Parameter/gradient dtype: float32, bfloat16, float16, float64 "
             "(the reference Configuration's dtype, configuration.py:26-101)")
    add("--compute-dtype", type=str, default=None,
        help="Forward/backward compute dtype; default = --dtype. "
             "'--dtype float32 --compute-dtype bfloat16' = TPU mixed "
             "precision (bf16 MXU matmuls, f32 master weights/momentum/GAR)")
    add("--nb-steps", type=int, default=-1,
        help="Number of (additional) training steps, negative for no limit")
    add("--nb-workers", type=int, default=11, help="Total number of workers")
    add("--nb-for-study", type=int, default=11,
        help="Gradients to compute for study purposes only")
    add("--nb-for-study-past", type=int, default=20,
        help="Past gradients kept for the curvature metric")
    add("--nb-decl-byz", type=int, default=4,
        help="Number of declared Byzantine workers")
    add("--nb-real-byz", type=int, default=0,
        help="Number of actually Byzantine workers")
    add("--init-multi", type=str, default=None,
        help="Multi-dimensional parameter init algorithm")
    add("--init-multi-args", nargs="*",
        help="key:value args for --init-multi")
    add("--init-mono", type=str, default=None,
        help="Mono-dimensional parameter init algorithm")
    add("--init-mono-args", nargs="*",
        help="key:value args for --init-mono")
    add("--gar", type=str, default="average", help="Aggregation rule")
    add("--gar-args", nargs="*", help="key:value args for the GAR")
    add("--gars", type=str, default=None,
        help="Random per-step GAR mixture: 'name[,freq[,json-args]];...'")
    add("--gars-per-call", action="store_true",
        help="Re-draw the --gars mixture GAR on every defense invocation "
             "(incl. inside adaptive attacks' line searches) — the "
             "reference's semantics; default draws once per step")
    add("--no-grouped-workers", action="store_true",
        help="Disable the merged-batch grouped honest phase (always use the "
             "vmapped per-worker path, even for models that provide the "
             "faster grouped execution)")
    add("--gar-diagnostics", action="store_true", default=False,
        help="Run the defense through its in-jit diagnostics kernel and "
             "append the aggregation-forensics columns to the study CSV "
             "('Sel workers', 'Dist honest med', 'Var/norm ratio', 'Clip "
             "frac', 'Suspicion max'), feeding the per-worker suspicion "
             "tracker (obs/forensics.py: suspect_worker telemetry events). "
             "Off by default: the diagnostic aux rides the compiled step "
             "as extra outputs (measured overhead documented in README)")
    add("--health", action="store_true", default=False,
        help="Numerics flight recorder: compute the in-jit tensor-health "
             "vector every step (engine/health.py — fixed-bin log-scale "
             "histogram of submitted-momentum norms, the paper's Var/norm "
             "ratio, weight/update norms, per-phase NaN/Inf counts), "
             "append the HEALTH_COLUMNS to the study CSV and feed the "
             "host-side SPC monitor (obs/health: EWMA+MAD z-scores with "
             "sustained-run rules, health_anomaly/health_cleared telemetry "
             "events, health_blackbox.json post-mortem ring). Off by "
             "default; when off the compiled step is byte-identical to "
             "the pre-health program")
    add("--rollback-on-anomaly", action="store_true", default=False,
        help="Upgrade the divergence-rollback trigger from 'training "
             "state went non-finite' to 'non-finite OR sustained health "
             "anomaly' (implies --health; needs '--rollback-budget'): the "
             "SPC monitor's rising anomaly edge rolls the run back to the "
             "last good checkpoint BEFORE the state is destroyed, reusing "
             "the pipelined rollback machinery")
    add("--attack", type=str, default="nan", help="Attack to use")
    add("--attack-args", nargs="*", help="key:value args for the attack")
    add("--fault-plan", type=str, default=None,
        help="JSON fault plan (faults.FaultPlan): deterministic per-step "
             "system faults — stragglers, dropped workers, corrupted/NaN "
             "shards, duplicated submissions, device loss — injected into "
             "the stacked gradient batch before aggregation, with the "
             "plan's degradation policy (NaN-quarantine, dynamic quorum, "
             "download retry). Adds the 'Faults injected'/'Workers "
             "active'/'Quorum f' columns to the study CSV")
    add("--model", type=str, default="simples-conv", help="Model to train")
    add("--model-args", nargs="*", help="key:value args for the model")
    add("--loss", type=str, default="nll", help="Loss to use")
    add("--loss-args", nargs="*", help="key:value args for the loss")
    add("--criterion", type=str, default="top-k", help="Criterion to use")
    add("--criterion-args", nargs="*", help="key:value args for the criterion")
    add("--dataset", type=str, default="mnist", help="Dataset to use")
    # Beyond-reference: the reference's make_datasets forwards no custom
    # kwargs (reference `attack.py:530`), so split-parameterized torchvision
    # datasets (e.g. EMNIST) are unreachable from its CLI; this extends the
    # uniform `key:value` mini-language to the dataset loader
    add("--dataset-args", nargs="*",
        help="key:value args for the dataset loader (e.g. split:balanced)")
    add("--batch-size", type=int, default=25, help="Training batch size")
    add("--batch-size-test", type=int, default=100, help="Test batch size")
    add("--batch-size-test-reps", type=int, default=100,
        help="Number of test batches per evaluation")
    add("--no-transform", action="store_true", default=False,
        help="Disable dataset transformations (normalization, flips)")
    add("--download", action="store_true", default=False,
        help="Allow fetching missing raw datasets from their published "
             "URLs with checksum verification (reference torchvision "
             "download=True, `experiments/dataset.py:296`; equivalent to "
             "BMT_DOWNLOAD=1)")
    add("--learning-rate", type=float, default=0.01, help="Learning rate")
    add("--learning-rate-decay", type=int, default=5000,
        help="Hyperbolic half-decay time, non-positive for no decay")
    add("--learning-rate-decay-delta", type=int, default=1,
        help="Steps between two learning-rate updates")
    add("--learning-rate-schedule", type=str, default=None,
        help="Piecewise schedule '<init lr>[,<from step>,<new lr>]*'")
    add("--momentum", type=float, default=0.9, help="Momentum")
    add("--dampening", type=float, default=0., help="Dampening")
    add("--momentum-nesterov", action="store_true", default=False,
        help="Nesterov lookahead variant")
    add("--momentum-at", type=str, default="update",
        help="Momentum placement: 'update', 'server' or 'worker'")
    add("--weight-decay", type=float, default=0., help="Weight decay")
    add("--optimizer", type=str, default="sgd",
        help="Optimizer applying the final update (default 'sgd' = the "
             "reference's torch-SGD semantics, reference attack.py:543-545)")
    add("--optimizer-args", nargs="*", help="key:value args for the optimizer")
    add("--trace-dir", type=str, default=None,
        help="Capture a jax.profiler trace of the first steps into this "
             "directory (opt-in, like the reference's TimedContext tools)")
    add("--attribution", action="store_true", default=False,
        help="Phase-attributed device profiling: trace exactly one fused "
             "chunk — deterministically, the first chunk whose program "
             "shape has already compiled and run once — and attribute its "
             "device time to the "
             "engine's named phases (honest/attack/gar/update/metrics), "
             "op classes (MXU vs memory-bound vs relayout copies) and the "
             "host gap — written as 'attribution.json' in the result "
             "directory with an 'attribution' telemetry event "
             "(obs/attrib; needs '--result-directory'). The flag only "
             "adds a one-chunk profiler window plus one throwaway "
             "compile; the compiled step program itself is unchanged")
    add("--telemetry", action="store_true", default=False,
        help="Record run telemetry — 'telemetry.jsonl' (spans/events/"
             "counters/gauges) and an atomic 'heartbeat.json' in the result "
             "directory. Default: ON whenever '--result-directory' is set "
             "(there is nowhere to write otherwise); this flag only makes "
             "the intent explicit")
    add("--no-telemetry", action="store_true", default=False,
        help="Disable run telemetry (no telemetry.jsonl, no heartbeat)")
    add("--telemetry-interval", type=int, default=50,
        help="Steps between telemetry samples: each sample drains the "
             "dispatch pipeline once for a device-honest step time, then "
             "records steps/s, host RSS and refreshes the heartbeat")
    add("--telemetry-mfu", action="store_true", default=False,
        help="Also estimate MFU: count the step program's logical FLOPs "
             "once (one throwaway compile at the first dispatch, bench.py's "
             "recipe) and add an 'mfu' gauge where the chip's bf16 peak is "
             "known")
    add("--l1-regularize", type=float, default=None,
        help="L1 loss regularization factor")
    add("--l2-regularize", type=float, default=None,
        help="L2 loss regularization factor")
    add("--gradient-clip", type=float, default=None,
        help="Per-gradient L2 clip threshold")
    add("--nb-local-steps", type=int, default=1,
        help="Local SGD steps per global step (implemented here; the "
             "reference advertises but disables it)")
    add("--load-checkpoint", type=str, default=None,
        help="Checkpoint to resume from")
    add("--auto-resume", action="store_true", default=False,
        help="Restart from the newest VALID checkpoint found in the result "
             "directory (torn/corrupt tails are skipped — checkpoints are "
             "written atomically with an integrity footer). The study/eval "
             "CSVs are truncated to the resume step and appended to, so an "
             "interrupted run's concatenated output equals an "
             "uninterrupted run's. When a resume actually happens, "
             "'--nb-steps' counts TOTAL steps from step 0 (supervisors "
             "re-issue the same command line); cold starts are unaffected")
    add("--keep-checkpoints", type=int, default=0,
        help="Retention: keep only this run's newest N checkpoints "
             "(manifest-driven GC at save time), 0 to keep all")
    add("--checkpoint-mirror", type=str, default=None,
        help="Off-slice checkpoint mirror: every checkpoint is also "
             "written (atomically, same integrity footer) into this "
             "second directory, and '--auto-resume' scans BOTH "
             "directories for the newest valid checkpoint — so losing "
             "the run's local storage (a dead host in a multi-host "
             "fleet, a preempted slice's scratch disk) costs nothing")
    add("--rollback-budget", type=int, default=0,
        help="Divergence rollback: when the training state goes non-finite "
             "mid-run, restore the last good checkpoint, re-seed the step "
             "RNG fold and continue — at most this many times per process "
             "(0 disables; needs '--checkpoint-delta' with a result "
             "directory). Exhausting the budget fails the run (exit 1) so "
             "a supervisor can retry it")
    add("--rollback-tighten-quorum", action="store_true", default=False,
        help="After each rollback, also raise the declared Byzantine count "
             "f by one (only while every defense's contract still holds) "
             "and rebuild the step program — trades a recompile for a "
             "stricter quorum on the retried trajectory")
    add("--result-directory", type=str, default=None,
        help="Directory for results (eval/study CSVs, checkpoints)")
    add("--evaluation-delta", type=int, default=100,
        help="Steps between evaluations, 0 for none")
    add("--checkpoint-delta", type=int, default=0,
        help="Steps between checkpoints, 0 for none")
    add("--user-input-delta", type=int, default=0,
        help="Steps between interactive prompts, 0 for none")
    add("--steps-per-program", type=int, default=8,
        help="Training steps fused into one compiled dispatch (lax.scan); "
             "milestones always force a boundary, so the per-step trajectory "
             "and CSV output are identical to 1 (which disables fusion). "
             "Each distinct residual window (when a milestone delta is not a "
             "multiple of this) compiles a separate program — pick a divisor "
             "of the evaluation/checkpoint deltas to avoid extra compiles")
    add("--mesh", type=str, default=None,
        help="Multi-chip (workers, model) mesh: 'auto' (all devices on the "
             "worker axis), 'W' or 'WxM' (e.g. '4x2' = 4-way worker data "
             "parallelism x 2-way parameter sharding). Batches shard along "
             "workers, parameters/momentum along model; XLA inserts the ICI "
             "collectives (all-gather of gradient rows into the GAR, psum'd "
             "distance Grams)")
    return parser.parse_args(sys.argv[1:] if argv is None else argv)


def _postprocess(args):
    """Derivations and checks (reference `attack.py:242-313`)."""
    for name in ("init_multi", "init_mono", "gar", "attack", "model", "loss",
                 "criterion", "dataset", "optimizer"):
        name = f"{name}_args"
        keyval = getattr(args, name)
        setattr(args, name, utils.parse_keyval(keyval))
    args.nb_honests = args.nb_workers - args.nb_real_byz
    if args.nb_honests < 0:
        utils.fatal(f"Invalid arguments: there are more real Byzantine "
                    f"workers ({args.nb_real_byz}) than total workers "
                    f"({args.nb_workers})")
    if args.nb_decl_byz > args.nb_workers:
        utils.fatal(f"Invalid arguments: there are more declared Byzantine "
                    f"workers ({args.nb_decl_byz}) than total workers "
                    f"({args.nb_workers})")
    # Learning rate plan (reference `attack.py:253-289`)
    if args.learning_rate_schedule is None:
        if args.learning_rate <= 0:
            utils.fatal(f"Invalid arguments: non-positive learning rate "
                        f"{args.learning_rate}")
        if args.learning_rate_decay_delta <= 0:
            utils.fatal(f"Invalid arguments: non-positive learning rate "
                        f"decay delta {args.learning_rate_decay_delta}")

        def compute_new_learning_rate(steps):
            if (args.learning_rate_decay > 0
                    and steps % args.learning_rate_decay_delta == 0):
                return args.learning_rate / (steps / args.learning_rate_decay + 1)
            return None

        def initial_lr(steps):
            # lr in effect at loop entry: the value set at the most recent
            # update boundary (args.learning_rate when no decay — the
            # reference seeds the optimizer with it, `attack.py:544`)
            if args.learning_rate_decay <= 0:
                return args.learning_rate
            last = steps - steps % args.learning_rate_decay_delta
            return args.learning_rate / (last / args.learning_rate_decay + 1)
    else:
        numbers = args.learning_rate_schedule.split(",")
        try:
            flat = tuple(float(x) if i % 2 == 0 else int(x)
                         for i, x in enumerate(numbers))
        except ValueError as err:
            utils.fatal(f"Invalid arguments: malformed learning rate "
                        f"schedule {args.learning_rate_schedule!r} ({err})")
        if len(flat) % 2 == 0:
            utils.fatal(f"Invalid arguments: learning rate schedule "
                        f"{args.learning_rate_schedule!r} must have the form "
                        f"'<init lr>[,<from step>,<new lr>]*'")
        schedule = [(0, flat[0])]
        for i in range(1, len(flat), 2):
            step, lr = flat[i], flat[i + 1]
            if step <= schedule[-1][0]:
                utils.fatal("Invalid arguments: learning rate schedule step "
                            "numbers must be strictly increasing")
            schedule.append((step, lr))

        def compute_new_learning_rate(steps):
            for step, lr in schedule:
                if steps == step:
                    return lr
            return None

        def initial_lr(steps):
            current = schedule[0][1]
            for step, lr in schedule:
                if step <= steps:
                    current = lr
            return current
    args.compute_new_learning_rate = compute_new_learning_rate
    args.initial_lr = initial_lr
    if args.momentum_at not in ("update", "server", "worker"):
        utils.fatal_unavailable(("update", "server", "worker"),
                                args.momentum_at, what="momentum position")
    if args.nb_local_steps < 1:
        utils.fatal(f"Invalid arguments: non-positive number of local steps "
                    f"{args.nb_local_steps}")
    # A loaded checkpoint carries the full device PRNG state and (normally)
    # the host sampler snapshots, so a fixed seed no longer has to be
    # discarded; whether the resume is bit-exact is decided at load time,
    # where the checkpoint's actual sampler payload is known (see `main`).
    if args.auto_resume:
        if args.load_checkpoint is not None:
            utils.fatal("Invalid arguments: '--auto-resume' and "
                        "'--load-checkpoint' are mutually exclusive "
                        "(auto-resume scans the result directory itself)")
        if args.result_directory is None:
            utils.fatal("Invalid arguments: '--auto-resume' requires "
                        "'--result-directory'")
    if args.keep_checkpoints < 0:
        utils.fatal(f"Invalid arguments: negative checkpoint retention "
                    f"{args.keep_checkpoints}")
    if args.checkpoint_mirror is not None and args.result_directory is None:
        utils.warning("'--checkpoint-mirror' needs '--result-directory' "
                      "(there is no primary to mirror); mirror disabled")
        args.checkpoint_mirror = None
    if args.telemetry and args.no_telemetry:
        utils.fatal("Invalid arguments: '--telemetry' and '--no-telemetry' "
                    "are mutually exclusive")
    if args.telemetry_interval < 1:
        utils.fatal(f"Invalid arguments: non-positive telemetry interval "
                    f"{args.telemetry_interval}")
    if args.telemetry and args.result_directory is None:
        utils.warning("'--telemetry' needs '--result-directory' (there is "
                      "nowhere to write the timeline); telemetry disabled")
    if args.attribution and args.result_directory is None:
        utils.warning("'--attribution' needs '--result-directory' (there "
                      "is nowhere to write the trace window and "
                      "attribution.json); attribution disabled")
        args.attribution = False
    if args.gar_diagnostics and (args.result_directory is None
                                 or args.nb_for_study < 1):
        utils.warning("'--gar-diagnostics' needs the study pipeline "
                      "('--nb-for-study' with '--result-directory'); "
                      "diagnostics disabled")
        args.gar_diagnostics = False
    if args.rollback_budget < 0:
        utils.fatal(f"Invalid arguments: negative rollback budget "
                    f"{args.rollback_budget}")
    if args.rollback_budget > 0 and (args.result_directory is None
                                     or args.checkpoint_delta <= 0):
        utils.warning("'--rollback-budget' needs periodic checkpoints "
                      "('--checkpoint-delta' with '--result-directory'); "
                      "rollback disabled")
        args.rollback_budget = 0
    if args.rollback_on_anomaly and not args.health:
        args.health = True  # the early-warning trigger needs the stream
    if args.health and (args.result_directory is None
                        or args.nb_for_study < 1):
        utils.warning("'--health' needs the study pipeline "
                      "('--nb-for-study' with '--result-directory'); "
                      "health columns disabled")
        args.health = False
        args.rollback_on_anomaly = False
    if args.rollback_on_anomaly and args.rollback_budget <= 0:
        utils.warning("'--rollback-on-anomaly' needs '--rollback-budget' "
                      "(there is no rollback machinery to trigger); "
                      "anomaly trigger disabled")
        args.rollback_on_anomaly = False
    # Study coercions (reference `attack.py:301-313`)
    if args.result_directory is None:
        args.nb_for_study = 0
        args.nb_for_study_past = 0
    else:
        if args.nb_for_study_past < 1:
            utils.warning("At least one gradient must exist in the past to "
                          "study honest curvature; set '--nb-for-study-past 1'")
            args.nb_for_study_past = 1
        elif math.isclose(args.momentum, 0.0) and args.nb_for_study_past > 1:
            utils.warning("Momentum is (almost) zero; set "
                          "'--nb-for-study-past 1'")
            args.nb_for_study_past = 1
    return args


def _parse_gars(spec):
    """Parse the `--gars 'name,freq,json;...'` mixture string into
    `[(gar, cumulative_freq, kwargs)]` (reference `attack.py:467-517`)."""
    freq_sum = 0.0
    defenses = []
    for info in spec.split(";"):
        info = info.split(",", maxsplit=2)
        name = info[0].strip()
        freq = 1.0
        if len(info) >= 2:
            raw = info[1].strip()
            freq = 1.0 if raw == "-" else float(raw)
        conf = {}
        if len(info) >= 3:
            try:
                conf = json.loads(info[2].strip())
            except json.decoder.JSONDecodeError as err:
                utils.fatal(f"Invalid GAR arguments for GAR {name!r}: "
                            f"{str(err).lower()}")
            if not isinstance(conf, dict):
                utils.fatal(f"Invalid GAR arguments for GAR {name!r}: "
                            f"expected a dictionary")
        if name not in ops_mod.gars:
            utils.fatal_unavailable(ops_mod.gars, name, what="aggregation rule")
        freq_sum += freq
        defenses.append((ops_mod.gars[name], freq_sum, conf))
    return defenses


def _config_text(args):
    """Human-readable run configuration (simplified tree rendering of the
    reference's `cmd_make_tree`, `attack.py:314-397`)."""
    lines = ["Configuration:"]
    for name in sorted(vars(args)):
        if name.startswith("_") or callable(getattr(args, name)):
            continue
        lines.append(f"  · {name} - {getattr(args, name)}")
    return os.linesep.join(lines)


class _ResultFiles:
    """`result_make`/`result_get`/`result_store` parity
    (reference `attack.py:403-448`): '# '-prefixed tab-separated header,
    rows prefixed with the line separator (no trailing newline).

    Crash recovery additions: `make(..., resume_step=s)` keeps an existing
    file's rows strictly below `s` (the rows a preempted predecessor wrote
    before its last valid checkpoint) instead of truncating everything, and
    `truncate(s)` rewinds every open file to below `s` mid-run (divergence
    rollback) — so the on-disk rows always form one contiguous, duplicate-
    free trajectory."""

    def __init__(self, directory):
        self.directory = directory
        self._fds = {}
        self._headers = {}

    def make(self, name, *fields, resume_step=None):
        if self.directory is None:
            raise RuntimeError("No result is to be output")
        if name in self._fds:
            raise KeyError(f"Name {name!r} is already bound to a result file")
        header = "# " + "\t".join(str(field) for field in fields)
        path = self.directory / name
        kept = ()
        if resume_step is not None and path.is_file():
            kept = self._surviving_rows(path, header, resume_step)
        fd = path.open("w")
        fd.write(header)
        for row in kept:
            fd.write(os.linesep + row)
        fd.flush()
        self._fds[name] = fd
        self._headers[name] = header

    @staticmethod
    def _surviving_rows(path, header, limit_step):
        """Rows of `path` strictly below `limit_step`, dropping rows from a
        different schema (header mismatch), torn tails (wrong field count —
        a kill can land mid-row-write) and unparsable step numbers."""
        try:
            lines = path.read_text().split(os.linesep)
        except OSError:
            return ()
        if not lines or lines[0] != header:
            return ()
        nb_fields = len(header[2:].split("\t"))
        kept = []
        for line in lines[1:]:
            fields = line.split("\t")
            if len(fields) != nb_fields:
                continue
            try:
                step = int(fields[0])
            except ValueError:
                continue
            if step < limit_step:
                kept.append(line)
        return tuple(kept)

    def truncate(self, step):
        """Rewind every open result file to rows strictly below `step`
        (divergence rollback: the rows past the restored checkpoint belong
        to the trajectory being abandoned)."""
        if self.directory is None:
            return
        for name in list(self._fds):
            self._fds[name].flush()
            self._fds[name].close()
            path = self.directory / name
            header = self._headers[name]
            kept = self._surviving_rows(path, header, step)
            fd = path.open("w")
            fd.write(header)
            for row in kept:
                fd.write(os.linesep + row)
            fd.flush()
            self._fds[name] = fd

    def get(self, name):
        if self.directory is None:
            return None
        return self._fds.get(name)

    def store(self, fd, *entries):
        fd.write(os.linesep + "\t".join(str(entry) for entry in entries))
        fd.flush()

    def close(self):
        for fd in self._fds.values():
            fd.close()


def main(argv=None):
    """Run one experiment (the reference's whole `attack.py` flow)."""
    # Graceful exit latch (reference `attack.py:41-45`)
    exit_trigger, exit_is_requested = utils.onetime(None)
    # SIGUSR1 arms an on-demand one-chunk jax.profiler window on a LIVE run
    # (serviced at the next loop iteration; see the training loop)
    profile_request = [False]
    try:
        signal.signal(signal.SIGINT, lambda *_: exit_trigger())
        signal.signal(signal.SIGTERM, lambda *_: exit_trigger())
        signal.signal(signal.SIGUSR1,
                      lambda *_: profile_request.__setitem__(0, True))
    except (ValueError, AttributeError):
        pass  # Not in the main thread, or a platform without SIGUSR1

    with utils.Context("cmdline", "info"):
        args = _postprocess(process_commandline(argv))

    with utils.Context("setup", "info"):
        # Device selection: 'auto' = JAX default platform. An explicit
        # --device pins jax_platforms to that backend alone, which would
        # make a different --device-gar platform unreachable — include it
        # in the (priority-ordered) platform list so both backends load.
        device_gar = (args.device_gar or "same").lower()
        device_gar_active = device_gar not in ("same", "")
        if args.device.lower() not in ("auto", ""):
            platforms = args.device.lower()
            if device_gar_active and device_gar != platforms:
                platforms = f"{platforms},{device_gar}"
            jax.config.update("jax_platforms", platforms)
        # Dtype selection (reference `attack.py:461`, Configuration dtype)
        from byzantinemomentum_tpu.engine.config import DTYPES
        for name in (args.dtype, args.compute_dtype):
            if name is not None and name not in DTYPES:
                utils.fatal_unavailable(sorted(set(DTYPES)), name,
                                        what="dtype")
        if jnp.float64 in (DTYPES[args.dtype],
                           DTYPES[args.compute_dtype or args.dtype]):
            jax.config.update("jax_enable_x64", True)
        if device_gar_active:
            if args.mesh is not None:
                utils.fatal("'--device-gar' and '--mesh' are mutually "
                            "exclusive (a mesh shards the fused step)")
            try:
                jax.devices(device_gar)
            except RuntimeError as err:
                utils.fatal(
                    f"Invalid '--device-gar {args.device_gar}': {err}")
            if args.steps_per_program > 1:
                utils.info("'--device-gar' hops devices every step; "
                           "multi-step fusion disabled")
                args.steps_per_program = 1
        # Seeding (reference `attack.py:453-459`; JAX PRNG is explicit)
        reproducible = args.seed >= 0
        seed = args.seed if reproducible else int.from_bytes(os.urandom(4), "little")
        np.random.seed(seed % 2**32)
        root_key = jax.random.PRNGKey(seed)

        # Defense(s)
        if args.gars is None:
            if args.gar not in ops_mod.gars:
                utils.fatal_unavailable(ops_mod.gars, args.gar,
                                        what="aggregation rule")
            defenses = [(ops_mod.gars[args.gar], 1.0, args.gar_args)]
        else:
            defenses = _parse_gars(args.gars)
            args.gar_args = {}
        # Attack
        if args.attack not in attacks_mod.attacks:
            utils.fatal_unavailable(attacks_mod.attacks, args.attack,
                                    what="attack")
        attack = attacks_mod.attacks[args.attack]
        # Fault plan (parsed before the datasets: its policy parameterizes
        # the download retry/backoff path, `data/sources.py:_fetch`)
        fault_plan = None
        fault_schedule = None
        if args.fault_plan is not None:
            from byzantinemomentum_tpu import faults as faults_mod
            try:
                fault_plan = faults_mod.FaultPlan.load(args.fault_plan)
            except (OSError, ValueError, TypeError) as err:
                utils.fatal(f"Unable to load fault plan "
                            f"{args.fault_plan!r}: {err}")
            message = fault_plan.validate(args.nb_workers, args.nb_honests)
            if message is not None:
                utils.fatal(f"Fault plan {args.fault_plan!r} cannot be "
                            f"used: {message}")
            policy = fault_plan.policy
            os.environ.setdefault("BMT_FETCH_ATTEMPTS",
                                  str(policy.fetch_attempts))
            os.environ.setdefault("BMT_FETCH_BACKOFF",
                                  str(policy.fetch_backoff))
            os.environ.setdefault("BMT_FETCH_TIMEOUT",
                                  str(policy.fetch_timeout))
            fault_schedule = faults_mod.build_schedule(
                fault_plan, nb_workers=args.nb_workers,
                nb_honests=args.nb_honests)
            if fault_schedule is None:
                utils.info("Fault plan has no events; the fault machinery "
                           "stays out of the compiled step entirely")
        # Model
        model_def = models_mod.build(args.model, **args.model_args)
        # Datasets
        if args.download:
            os.environ["BMT_DOWNLOAD"] = "1"
        data_setup_t0 = time.monotonic()
        trainset, testset = data_mod.make_datasets(
            args.dataset, args.batch_size, args.batch_size_test,
            no_transform=args.no_transform, seed=seed % 2**32,
            **args.dataset_args)
        # Emitted as a telemetry event once the recorder exists (the result
        # directory — where the recorder writes — is established later)
        data_setup_s = time.monotonic() - data_setup_t0
        # Losses (reference `attack.py:534-541`)
        loss = losses_mod.Loss(args.loss, **args.loss_args)
        if args.l1_regularize is not None:
            loss = loss + args.l1_regularize * losses_mod.Loss("l1")
        if args.l2_regularize is not None:
            loss = loss + args.l2_regularize * losses_mod.Loss("l2")
        criterion = losses_mod.Criterion(args.criterion, **args.criterion_args)

        # Engine
        cfg = EngineConfig(
            nb_workers=args.nb_workers, nb_decl_byz=args.nb_decl_byz,
            nb_real_byz=args.nb_real_byz,
            nb_for_study=(args.nb_for_study if args.result_directory else 0),
            nb_for_study_past=max(args.nb_for_study_past, 1),
            momentum=args.momentum, dampening=args.dampening,
            nesterov=args.momentum_nesterov, momentum_at=args.momentum_at,
            weight_decay=args.weight_decay, gradient_clip=args.gradient_clip,
            nb_local_steps=args.nb_local_steps,
            gars_per_call=args.gars_per_call,
            grouped_workers=not args.no_grouped_workers,
            dtype=args.dtype, compute_dtype=args.compute_dtype,
            fault_quarantine=(fault_plan.policy.nan_quarantine
                              if fault_plan is not None else True),
            fault_dynamic_quorum=(fault_plan.policy.dynamic_quorum
                                  if fault_plan is not None else True),
            gar_diagnostics=args.gar_diagnostics,
            health=args.health)
        from byzantinemomentum_tpu import optim
        optimizer = optim.build(args.optimizer,
                                weight_decay=args.weight_decay,
                                **args.optimizer_args)

        def build_engine_with(engine_cfg):
            """The jitted engine for a config — called once at setup and
            again when a divergence rollback tightens the quorum (the
            declared f is a trace-time constant, so a stricter quorum is a
            program rebuild)."""
            return build_engine(
                cfg=engine_cfg, model_def=model_def, loss=loss,
                criterion=criterion, defenses=defenses, attack=attack,
                attack_kwargs=args.attack_args, optimizer=optimizer,
                faults=fault_schedule)

        engine = build_engine_with(cfg)
        # Multi-chip mesh: shard the step over a (workers, model) device grid
        mesh = None
        if args.mesh is not None:
            from byzantinemomentum_tpu.parallel import make_mesh
            spec = args.mesh.strip().lower()
            try:
                if spec == "auto":
                    mesh = make_mesh()
                else:
                    w, _, m = spec.partition("x")
                    w = int(w)
                    m = int(m) if m else 1
                    if w < 1 or m < 1:
                        raise ValueError(
                            f"mesh axes must be positive, got {w}x{m}")
                    mesh = make_mesh(w * m, model_parallel=m)
            except ValueError as err:
                utils.fatal(f"Invalid '--mesh {args.mesh}': {err}")
            workers_ax = mesh.shape["workers"]
            S_check = max(args.nb_workers - args.nb_real_byz,
                          args.nb_for_study if args.result_directory else 0)
            if S_check % workers_ax != 0:
                utils.fatal(
                    f"Invalid '--mesh {args.mesh}': the {S_check} sampled "
                    f"gradients per step must divide evenly over the "
                    f"{workers_ax}-way worker axis")
        # Device-resident input fast path: stage the datasets in device
        # memory once; per step only (S, B) index/flip arrays cross the host
        # boundary (see `data/device.py`). Under a mesh the batches are
        # host-staged instead so they shard along the worker axis.
        from byzantinemomentum_tpu.data.device import DeviceData
        # The indexed fast path bypasses `step_fn`, so it is incompatible
        # with heterogeneous GAR placement (and with a mesh, see above)
        use_device_data = (mesh is None and not device_gar_active
                           and DeviceData.supports(trainset)
                           and DeviceData.supports(testset))
        if use_device_data:
            train_data, test_data = DeviceData.pair(trainset, testset)
            engine.attach_data(train_data, test_data)

        # One-time contract validation (the reference validates on every call
        # through the 'checked' wrappers, `aggregators/__init__.py:52-61`;
        # with a single compiled program, validating once at setup is the
        # equivalent guarantee)
        dummy = jnp.zeros((args.nb_workers, 2), jnp.float32)
        for gar, _, kwargs in defenses:
            message = gar.check(gradients=dummy, f=args.nb_decl_byz, **kwargs)
            if message is not None:
                utils.fatal(f"Aggregation rule {gar.name!r} cannot be used: "
                            f"{message}")
        message = attack.check(
            grad_honests=jnp.zeros((args.nb_honests, 2), jnp.float32),
            f_decl=args.nb_decl_byz, f_real=args.nb_real_byz,
            defense=lambda **kw: None, **args.attack_args)
        if message is not None:
            utils.fatal(f"Attack {attack.name!r} cannot be used: {message}")

        # Result directory (reference `attack.py:549-591`)
        results = None
        resume_step = None      # step an --auto-resume actually restarts at
        restart_count = 0       # times this run was auto-resumed (manifest)
        # Recovery columns ride the study CSV only when crash recovery is
        # on, mirroring the FAULT_COLUMNS opt-in schema policy
        recovery_active = args.auto_resume or args.rollback_budget > 0
        # Aggregation forensics (--gar-diagnostics): in-jit GAR aux out of
        # the step, host-side per-worker suspicion EWMA over it
        forensics_active = cfg.gar_diagnostics and cfg.study
        suspicion = (obs_mod.SuspicionTracker(args.nb_workers)
                     if forensics_active else None)
        # Numerics flight recorder (--health): in-jit health vector out of
        # the step, host-side SPC monitor over it (obs/health)
        health_active = cfg.health and cfg.study
        # The monitor's anomaly/clear edges also bump metrics-plane
        # counters (obs/metrics) so a scrape of the driver's registry
        # carries the same signal the telemetry stream does
        monitor = (obs_mod.HealthMonitor(
            metrics=obs_mod.metrics.MetricsRegistry(source="driver"))
            if health_active else None)
        if args.result_directory is not None:
            resdir = pathlib.Path(args.result_directory).resolve()
            try:
                resdir.mkdir(mode=0o755, parents=True, exist_ok=True)
            except OSError as err:
                utils.warning(f"Unable to create the result directory "
                              f"{str(resdir)!r} ({err}); no result stored")
                args.result_directory = None
                args.checkpoint_delta = 0
            else:
                args.result_directory = resdir
                if args.checkpoint_mirror is not None:
                    mirror_dir = pathlib.Path(args.checkpoint_mirror).resolve()
                    try:
                        mirror_dir.mkdir(mode=0o755, parents=True,
                                         exist_ok=True)
                    except OSError as err:
                        utils.warning(f"Unable to create the checkpoint "
                                      f"mirror {str(mirror_dir)!r} ({err}); "
                                      f"mirror disabled")
                        args.checkpoint_mirror = None
                    else:
                        args.checkpoint_mirror = mirror_dir
                if args.auto_resume:
                    found = checkpoint_mod.find_latest_valid_any(
                        (resdir, args.checkpoint_mirror))
                    if found is None:
                        utils.info("Auto-resume: no valid checkpoint in "
                                   f"{str(resdir)!r}; cold start")
                    else:
                        args.load_checkpoint = str(found)
                        resume_step = checkpoint_mod.checkpoint_step(found)
                        restart_count = checkpoint_mod.bump_restarts(resdir)
                        utils.info(f"Auto-resume: restart #{restart_count} "
                                   f"from {found.name} (step {resume_step})")
                results = _ResultFiles(resdir)
                if args.evaluation_delta > 0:
                    results.make("eval", "Step number", "Cross-accuracy",
                                 resume_step=resume_step)
                if args.nb_for_study > 0:
                    # Resilience columns appended only under a fault plan —
                    # fault-free runs keep the reference's exact CSV schema
                    study_columns = STUDY_COLUMNS + (
                        FAULT_COLUMNS if fault_schedule is not None else ())
                    if recovery_active:
                        study_columns = study_columns + RECOVERY_COLUMNS
                    if forensics_active:
                        study_columns = study_columns + FORENSIC_COLUMNS
                    if health_active:
                        study_columns = study_columns + HEALTH_COLUMNS
                    results.make("study", *study_columns,
                                 resume_step=resume_step)
                (resdir / "config").write_text(_config_text(args) + os.linesep)
                with (resdir / "config.json").open("w") as fd:
                    def jsonable(x):
                        return x if type(x) in (str, int, float, bool,
                                                type(None), dict, list) else str(x)
                    json.dump({k: jsonable(v) for k, v in vars(args).items()
                               if not k.startswith("_")
                               and not callable(getattr(args, k))},
                              fd, ensure_ascii=False, indent="\t")
        elif args.checkpoint_delta != 0:
            args.checkpoint_delta = 0
            utils.warning("Argument '--checkpoint-delta' ignored as no "
                          "'--result-directory' was specified")

        # Telemetry recorder: default-on for every run with a result
        # directory (the system timeline is as much a run artifact as the
        # study CSV); '--no-telemetry' opts out. Activated as the process's
        # recorder so deep layers (checkpoint.py, faults/) land on the
        # timeline too. Deactivate any recorder a previous in-process run
        # (tests call `main` repeatedly) left behind on an error path.
        obs_mod.deactivate()
        telem = None
        if args.result_directory is not None and not args.no_telemetry:
            try:
                telem = obs_mod.Telemetry(args.result_directory,
                                          interval=args.telemetry_interval)
            except OSError as err:
                utils.warning(f"Telemetry disabled: cannot open the "
                              f"timeline file ({err})")
            else:
                obs_mod.activate(telem)
                obs_mod.install_compile_listener(telem)
                telem.event("run_start", seed=seed,
                            restarts=restart_count,
                            resume_step=resume_step)
                telem.event("data_setup", seconds=round(data_setup_s, 3),
                            dataset=args.dataset)
                if resume_step is not None:
                    # The acceptance signal for supervised chaos runs: the
                    # resumed process stamps WHERE it restarted from
                    telem.event("restart", step=resume_step,
                                count=restart_count)

    # Load/initialize state (reference `attack.py:621-682`)
    with utils.Context("load", "info"):
        params, net_state = model_def.init(root_key)
        if args.init_multi or args.init_mono:
            params = apply_named_init(
                params, jax.random.fold_in(root_key, 2),
                init_multi=args.init_multi,
                init_multi_args=args.init_multi_args,
                init_mono=args.init_mono, init_mono_args=args.init_mono_args)
        state = engine.init(root_key, params=params, net_state=net_state)
        if args.load_checkpoint is not None:
            try:
                state, data_state = checkpoint_mod.load(
                    args.load_checkpoint, state, return_data=True)
            except utils.UserException:
                raise
            except Exception as err:  # bmt: noqa[BMT-E05] load reconciles arbitrary payload trees; any fault becomes one fatal with the file named
                utils.fatal(f"Unable to load checkpoint "
                            f"{args.load_checkpoint!r}: {err}")
            else:
                if data_state is not None:
                    try:
                        snaps = (data_state["train"], data_state["test"])
                        trainset.set_state(snaps[0])
                        testset.set_state(snaps[1])
                    except Exception as err:  # bmt: noqa[BMT-E05] sampler snapshots from old checkpoints vary by dataset; degrade to a warned partial restore
                        utils.warning(
                            f"Checkpoint sampler state only partially or not "
                            f"restored ({err}); resumed batch order may "
                            f"differ")
                    else:
                        # The checkpoint carries the device PRNG state AND
                        # the host sampler snapshots: the resume is
                        # bit-exact, and any fixed --seed only governed the
                        # (now superseded) initialization
                        if args.seed >= 0:
                            utils.info(
                                "Seed argument superseded by the "
                                "checkpoint's RNG and sampler state "
                                "(bit-exact resume)")
                else:
                    utils.warning(
                        "Checkpoint carries no sampler state; resumed batch "
                        "order (seeded or not) will differ from the "
                        "uninterrupted run")

    # Compile the (possibly mesh-sharded) step programs
    def make_step_programs(eng, st):
        """(step_fn, multi_fn, eval_many_fn) for an engine — shared by the
        initial compile and the rollback quorum-tightening rebuild."""
        if mesh is not None:
            from byzantinemomentum_tpu.parallel import (
                sharded_eval_many, sharded_train_multi, sharded_train_step)
            step = sharded_train_step(eng, mesh, st)
            multi = sharded_train_multi(eng, mesh, st)
            # Milestone evaluation shards only when the test batch divides
            # the worker axis; otherwise it stays on the (off-hot-path)
            # replicated program instead of failing at the first milestone
            if args.batch_size_test % mesh.shape["workers"] == 0:
                eval_many = sharded_eval_many(eng, mesh, st)
            else:
                eval_many = eng.eval_many
                utils.info(
                    f"Evaluation stays unsharded: --batch-size-test "
                    f"{args.batch_size_test} does not divide the "
                    f"{mesh.shape['workers']}-way worker axis")
            utils.info(f"Sharded over mesh {dict(mesh.shape)}")
            return step, multi, eval_many
        if device_gar_active:
            from byzantinemomentum_tpu.engine.step import make_device_gar_step
            utils.info(f"Defense phase placed on '{device_gar}' "
                       f"(per-step gradient hop)")
            # multi_fn unreachable: fusion forced to 1
            return (make_device_gar_step(eng, device_gar),
                    eng.train_multi, eng.eval_many)
        return eng.train_step, eng.train_multi, eng.eval_many

    step_fn, multi_fn, eval_many_fn = make_step_programs(engine, state)

    # Opt-in profiler trace of the early steps (TPU counterpart of the
    # reference's opt-in timing scopes, reference `tools/misc.py:307-343`)
    if args.trace_dir is not None:
        jax.profiler.start_trace(args.trace_dir)
        obs_mod.emit("profiler_trace_start", directory=str(args.trace_dir))

    # Training (reference `attack.py:685-885`)
    with utils.Context("training", "info"):
        # An ACTUAL auto-resume interprets --nb-steps as the TOTAL step
        # count from step 0: a supervisor re-issues the same command line
        # and the resumed run must stop where the uninterrupted run would
        # have (explicit --load-checkpoint keeps the additive semantics)
        steps_limit = (None if args.nb_steps < 0
                       else args.nb_steps if resume_step is not None
                       else int(state.steps) + args.nb_steps)
        fd_eval = results.get("eval") if results else None
        fd_study = results.get("study") if results else None
        current_lr = args.initial_lr(int(state.steps))
        # Dtype-dependent CSV precision (reference `attack.py:870`; bf16 has
        # f16-like mantissa width, so it shares the "%.4e" format)
        float_format = {
            jnp.float16: "%.4e", jnp.bfloat16: "%.4e",
            jnp.float32: "%.8e", jnp.float64: "%.16e",
        }.get(cfg.jnp_dtype, "%s")
        just_loaded = args.load_checkpoint is not None

        # Host-side mirrors of the step/datapoint counters: they advance
        # deterministically (+M steps, +M*batch*honests*local_steps points
        # per dispatched chunk), and reading them off the device state every
        # iteration would force a full sync per chunk — on tunneled
        # backends a ~100 ms round trip that idles the chip
        steps_host = int(state.steps)
        datapoints_host = int(state.datapoints)

        # Telemetry sampling state: a sample drains the pipeline once (the
        # StepTimer's device->host barrier) for a device-honest chunk time,
        # then records throughput/RSS gauges and refreshes the heartbeat.
        # Between samples the only telemetry cost is a deque append.
        rate_window = obs_mod.SlidingRate()
        step_timer = obs_mod.StepTimer()
        next_sample_step = steps_host
        mfu_flops = None   # logical FLOPs/step: lazy, False = gave up
        mfu_peak = None

        def _health_hb():
            """The heartbeat's `health` block (training-dynamics state
            next to liveness — the Jobs watchdog and the fleet liveness
            view read it); empty without the flight recorder."""
            return ({"health": monitor.summary()}
                    if monitor is not None else {})

        if telem is not None:
            try:
                mfu_peak = obs_mod.peak_flops(jax.devices()[0].device_kind)
            except RuntimeError:
                mfu_peak = None  # backend probe failed: MFU gauge stays off
            # First heartbeat before the first (slow: compile) dispatch, so
            # a supervisor watchdog sees a live signal immediately
            telem.heartbeat(step=steps_host, status="running",
                            **_health_hb())
        # (directory, from_step) of a live SIGUSR1 profiler window
        profile_active = None
        # --attribution: deterministic phase attribution of one traced
        # chunk. The window only opens on a chunk whose program shape (the
        # fused step count M) has ALREADY been dispatched once: the first
        # chunk of each shape carries its compile, and a compile inside
        # the window would both smear the forced device_step_ms sample and
        # balloon the xplane with host compile events (a ~60 s compile
        # traces to hundreds of MB — unparseable under the pure-python
        # protobuf backend). Milestone-residual windows (an M smaller than
        # --steps-per-program) therefore postpone the trace to the first
        # re-visit of a warm shape — still the same step window every run.
        attrib_armed = args.attribution
        attrib_seen_m = set()  # chunk shapes (M) already compiled+run
        attrib_window = None   # (trace dir, steps, hlo text, flops)

        def lower_hlo_text(dispatch_fn, dispatch_args):
            """Optimized-HLO text (+ cost-analysis FLOPs/step) of the
            program about to run, from the SAME jit object (`.lower` on
            the `_mode_jit` wrappers) so instruction names match the
            traced execution — the scope join CPU traces need. None-s when
            the dispatch path has no .lower (device-gar, sharded) or the
            throwaway compile fails: attribution then degrades to
            op-class-only buckets instead of crashing the run."""
            lower = getattr(dispatch_fn, "lower", None)
            if lower is None:
                return None, None
            try:
                compiled = lower(*dispatch_args).compile()
                return compiled.as_text(), obs_mod.flops_of_compiled(compiled)
            except Exception as err:  # bmt: noqa[BMT-E05] the throwaway AOT compile fails in backend-specific ways; attribution must degrade, never kill training
                utils.warning(f"Attribution HLO lowering failed ({err}); "
                              f"phase join degraded")
                return None, None

        def attribute_window(trace_dir, steps, hlo_text, flops, out_dir):
            """Attribute one CLOSED trace window and write
            `attribution.json` into `out_dir` (plus an 'attribution'
            telemetry event). Degrades to a warning when the trace is
            unreadable (absent xplane proto bindings, torn capture)."""
            from byzantinemomentum_tpu.obs import attrib
            try:
                kind = jax.devices()[0].device_kind
            except RuntimeError:
                kind = None
            try:
                att = attrib.attribute_trace(
                    str(trace_dir), steps, hlo_text=hlo_text,
                    flops_per_step=flops or (mfu_flops or None),
                    peak_flops=mfu_peak,
                    backend=jax.default_backend(), device_kind=kind)
            except (FileNotFoundError, ImportError, ValueError) as err:
                utils.warning(f"Attribution of {str(trace_dir)!r} failed "
                              f"({err})")
                return None
            path = attrib.write_attribution(out_dir, att)
            if telem is not None:
                telem.event(
                    "attribution", path=str(path), steps=steps,
                    total_ms=att["total_ms"],
                    relayout_ms=att["op_classes"]["relayout"],
                    host_gap_fraction=att["host_gap_fraction"],
                    mfu=att["mfu"],
                    phases={k: round(v["ms"], 5)
                            for k, v in att["phases"].items()
                            if v["ms"] > 0.0})
            utils.info(f"Attribution: {att['total_ms']:.3f} ms/step over "
                       f"{steps} traced steps -> {str(path)!r}")
            return att

        # Study metrics of the previously dispatched chunk, transferred
        # AFTER the next chunk is enqueued (depth-2 pipeline, same scheme
        # as bench.py): (device metrics, steps, datapoints, batch, M)
        pending_study = []
        # Depth-2 dispatch throttle for runs WITHOUT a study file: a tiny
        # device scalar from the previous chunk, transferred after the next
        # chunk is enqueued — bounds host run-ahead (and the device memory
        # pinned by in-flight input batches) without stalling the pipeline
        pending_sync = []

        def flush_study():
            if not pending_study:
                return
            p_metrics, p_steps, p_datapoints, p_batch, p_m, p_rollbacks = \
                pending_study.pop()
            p_metrics = jax.device_get(p_metrics)
            inc = p_batch * cfg.nb_honests * cfg.nb_local_steps
            for i in range(p_m):
                row = [p_steps + i, p_datapoints + i * inc]
                for column in STUDY_COLUMNS[2:-1]:
                    value = p_metrics[column]
                    value = value[i] if p_m > 1 else value
                    row.append(float_format % float(value))
                ar = p_metrics["Attack acceptation ratio"]
                row.append(float(ar[i] if p_m > 1 else ar))
                if fault_schedule is not None:
                    # Integer resilience counters (faults/quorum layer)
                    for column in FAULT_COLUMNS:
                        value = p_metrics[column]
                        row.append(int(value[i] if p_m > 1 else value))
                if recovery_active:
                    # Host-side crash-recovery counters (RECOVERY_COLUMNS):
                    # rollbacks as of the chunk's dispatch, restarts from
                    # the run manifest
                    row.append(p_rollbacks)
                    row.append(restart_count)
                if forensics_active:
                    # FORENSIC_COLUMNS: selection indices formatted from
                    # the in-graph mask, the in-graph scalars verbatim,
                    # and the suspicion EWMA folded per step (host-side,
                    # O(n) — the device only shipped the vectors)
                    def _per_step(key):
                        value = np.asarray(p_metrics[key])
                        return value[i] if p_m > 1 else value
                    sel = _per_step("Sel mask")
                    selected = np.nonzero(sel > 0)[0]
                    row.append(";".join(str(w) for w in selected) or "-")
                    for column in ("Dist honest med", "Var/norm ratio",
                                   "Clip frac"):
                        row.append(float_format % float(_per_step(column)))
                    active = (_per_step("Active mask")
                              if "Active mask" in p_metrics else None)
                    suspicion.update(p_steps + i, sel,
                                     distances=_per_step("Worker dist"),
                                     active=active)
                    row.append(float_format % suspicion.max())
                if health_active:
                    # HEALTH_COLUMNS: the in-jit health vector formatted
                    # for the CSV and folded into the SPC monitor (the
                    # anomaly/rollback trigger reads the monitor at the
                    # next loop top — pipelined like the isfinite flag)
                    def _hval(key):
                        value = np.asarray(p_metrics[key])
                        return value[i] if p_m > 1 else value
                    for column in ("Var ratio", "Weight norm",
                                   "Update norm", "Update/weight"):
                        row.append(float_format % float(_hval(column)))
                    hist = [int(c) for c in np.asarray(_hval("Norm hist"))]
                    row.append(";".join(str(c) for c in hist))
                    nonfinite = {}
                    for column in ("Nonfinite submitted",
                                   "Nonfinite aggregate",
                                   "Nonfinite state"):
                        nonfinite[column] = int(_hval(column))
                        row.append(nonfinite[column])
                    monitor.update(p_steps + i, {
                        "var_ratio": float(_hval("Var ratio")),
                        "update_ratio": float(_hval("Update/weight")),
                        "weight_norm": float(_hval("Weight norm")),
                        "update_norm": float(_hval("Update norm")),
                        "nonfinite": sum(nonfinite.values()),
                        "norm_hist": hist,
                    })
                results.store(fd_study, *row)
            if fault_schedule is not None and telem is not None:
                # The chunk's scheduled-fault total lands on the system
                # timeline too (the study CSV has the per-step values)
                injected = int(np.sum(np.asarray(
                    p_metrics["Faults injected"])))
                if injected:
                    telem.counter("faults_injected", injected)

        # --- divergence rollback (`--rollback-budget`): a depth-2 pipelined
        # health flag per dispatched chunk; a non-finite training state
        # restores the newest valid checkpoint, truncates the result CSVs
        # back to it, re-seeds the step RNG fold (so the retried trajectory
        # draws differently) and optionally tightens the quorum
        rollbacks = 0
        diverged = False
        pending_health = []
        health_enabled = args.rollback_budget > 0

        def tighten_quorum():
            nonlocal engine, cfg, step_fn, multi_fn, eval_many_fn
            new_f = cfg.nb_decl_byz + 1
            if new_f > args.nb_workers:
                utils.info("Quorum not tightened: f already equals n")
                return
            dummy = jnp.zeros((args.nb_workers, 2), jnp.float32)
            for gar, _, kwargs in defenses:
                message = gar.check(gradients=dummy, f=new_f, **kwargs)
                if message is not None:
                    utils.info(f"Quorum not tightened: {gar.name!r} cannot "
                               f"run with f={new_f} ({message})")
                    return
            import dataclasses
            cfg = dataclasses.replace(cfg, nb_decl_byz=new_f)
            engine = build_engine_with(cfg)
            if use_device_data:
                engine.attach_data(train_data, test_data)
            step_fn, multi_fn, eval_many_fn = make_step_programs(engine, state)
            utils.warning(f"Rollback: declared Byzantine count tightened "
                          f"to f={new_f} (step program rebuilt)")

        def roll_back(trigger="non-finite"):
            """Restore the last good checkpoint after a health trigger
            ('non-finite' state, or a sustained 'anomaly' under
            --rollback-on-anomaly); False when the run must give up
            (budget spent / nothing valid to restore)."""
            nonlocal state, steps_host, datapoints_host, current_lr, \
                just_loaded, rollbacks, fd_eval, fd_study
            rollbacks += 1
            if rollbacks > args.rollback_budget:
                utils.error(f"Health trigger ({trigger}) at step "
                            f"{steps_host} and the rollback budget "
                            f"({args.rollback_budget}) is exhausted; "
                            f"giving up")
                return False
            found = checkpoint_mod.find_latest_valid_any(
                (args.result_directory, args.checkpoint_mirror))
            if found is None:
                utils.error("Non-finite training state and no valid "
                            "checkpoint to roll back to; giving up")
                return False
            try:
                restored, data_state = checkpoint_mod.load(
                    found, state, return_data=True)
            except Exception as err:  # bmt: noqa[BMT-E05] a rollback target that fails to load for ANY reason means give up cleanly, not crash mid-recovery
                utils.error(f"Rollback reload of {found.name} failed "
                            f"({err}); giving up")
                return False
            if data_state is not None:
                try:
                    trainset.set_state(data_state["train"])
                    testset.set_state(data_state["test"])
                except Exception as err:  # bmt: noqa[BMT-E05] same degrade path as the resume sampler restore above — partial restore is warned, not fatal
                    utils.warning(f"Rollback sampler state only partially "
                                  f"restored ({err})")
            # Re-seed the step RNG fold: replaying the exact trajectory
            # that just diverged would diverge again
            state = restored._replace(
                rng=jax.random.fold_in(restored.rng, 0x5EED + rollbacks))
            pending_study.clear()
            pending_sync.clear()
            pending_health.clear()
            steps_host = int(state.steps)
            datapoints_host = int(state.datapoints)
            current_lr = args.initial_lr(steps_host)
            just_loaded = True
            if results is not None:
                # truncate() reopens the files — refresh the loop's handles
                results.truncate(steps_host)
                fd_eval = results.get("eval")
                fd_study = results.get("study")
            utils.warning(f"Rollback #{rollbacks}/{args.rollback_budget}: "
                          f"{trigger} health trigger; restored "
                          f"{found.name} (step {steps_host})")
            if telem is not None:
                telem.counter("rollbacks")
                telem.event("rollback", step=steps_host,
                            restored=found.name, trigger=trigger,
                            budget_left=args.rollback_budget - rollbacks)
                telem.heartbeat(step=steps_host, status="rolled-back",
                                **_health_hb())
            if args.rollback_tighten_quorum:
                tighten_quorum()
            return True

        # Chaos-test instrumentation (`tests/test_chaos.py`): die the hard
        # way at a step (preemption stand-in), or poison the parameters to
        # exercise the rollback path deterministically
        chaos_kill = os.environ.get("BMT_CHAOS_KILL_AT_STEP")
        chaos_kill = int(chaos_kill) if chaos_kill else None
        chaos_nan = os.environ.get("BMT_CHAOS_NAN_AT_STEP")
        chaos_nan = int(chaos_nan) if chaos_nan else None
        chaos_nan_repeat = os.environ.get("BMT_CHAOS_NAN_REPEAT") == "1"
        # Gradual-divergence hook (the early-warning acceptance surface):
        # scale the parameters by a factor per chunk past the step — the
        # norms blow up over several steps BEFORE overflowing to inf, so
        # the SPC anomaly must fire ahead of the isfinite flag
        chaos_blow = os.environ.get("BMT_CHAOS_BLOWUP_AT_STEP")
        chaos_blow = int(chaos_blow) if chaos_blow else None
        chaos_blow_factor = float(
            os.environ.get("BMT_CHAOS_BLOWUP_FACTOR", "1e12"))

        try:
            while not exit_is_requested():
                if chaos_kill is not None and steps_host >= chaos_kill:
                    os.kill(os.getpid(), signal.SIGKILL)
                # Health verdict of the previous chunk, BEFORE any milestone
                # can evaluate/checkpoint (never snapshots a poisoned
                # state). Two triggers, checked hard-signal first: the
                # pipelined isfinite flag, and — with --rollback-on-anomaly
                # — the SPC monitor's sustained-anomaly edge (the early
                # warning: it fires while the state is still finite)
                trigger = None
                if pending_health:
                    if not bool(np.asarray(pending_health.pop())):
                        trigger = "non-finite"
                if (trigger is None and args.rollback_on_anomaly
                        and monitor is not None
                        and monitor.rollback_pending()):
                    trigger = "anomaly"
                if trigger is not None:
                    if telem is not None:
                        telem.event("health_flag", step=steps_host,
                                    trigger=trigger)
                    if monitor is not None:
                        # The post-mortem BEFORE the trajectory rewinds:
                        # the ring holds the exact steps that went bad
                        monitor.dump_blackbox(args.result_directory,
                                              reason=trigger)
                    if not roll_back(trigger):
                        if telem is not None:
                            telem.event("divergence_giveup",
                                        step=steps_host)
                        if monitor is not None:
                            monitor.dump_blackbox(args.result_directory,
                                                  reason="divergence_giveup")
                        diverged = True
                        break
                    if monitor is not None:
                        monitor.note_rollback()
                    continue
                steps = steps_host
                milestone_evaluation = (args.evaluation_delta > 0
                                        and steps % args.evaluation_delta == 0)
                milestone_checkpoint = (args.checkpoint_delta > 0
                                        and steps % args.checkpoint_delta == 0)
                milestone_user_input = (args.user_input_delta > 0
                                        and steps % args.user_input_delta == 0)
                # Sampler snapshot BEFORE the evaluation consumes test batches,
                # so a resumed run replays this step's evaluation exactly
                # Milestones read/serialize device state (inherent sync) — any
                # buffered study rows are transferred first so the files stay
                # strictly ordered on disk
                if milestone_evaluation or milestone_checkpoint \
                        or milestone_user_input:
                    flush_study()
                data_snapshot = None
                if milestone_checkpoint and not just_loaded:
                    data_snapshot = {"train": trainset.get_state(),
                                     "test": testset.get_state()}
                if milestone_evaluation:
                    # One compiled program + one host transfer per evaluation
                    # (the reference runs batch_size_test_reps separate
                    # synchronous calls, `attack.py:709-715`). The float()
                    # reads make the whole span device-synchronous, so its
                    # duration is honest.
                    with obs_mod.span("eval", step=steps):
                        reps = args.batch_size_test_reps
                        if use_device_data:
                            idx, flips = test_data.sample_indices(reps)
                            res = engine.eval_many_indexed(
                                state.theta, state.net_state,
                                jnp.asarray(idx), jnp.asarray(flips))
                        else:
                            bxs, bys = zip(*(testset.sample()
                                             for _ in range(reps)))
                            res = eval_many_fn(
                                state.theta, state.net_state,
                                jnp.asarray(np.stack(bxs)),
                                jnp.asarray(np.stack(bys)))
                        acc = float(res[0]) / float(res[1])
                    utils.info(f"Accuracy (step {steps}): {acc * 100.:.2f}%")
                    if fd_eval is not None:
                        results.store(fd_eval, steps, acc)
                if milestone_checkpoint and not just_loaded:
                    filename = args.result_directory / f"checkpoint-{steps}"
                    try:
                        checkpoint_mod.save(filename, state,
                                            data_state=data_snapshot,
                                            keep=args.keep_checkpoints or None,
                                            mirror=args.checkpoint_mirror)
                    except Exception as err:  # bmt: noqa[BMT-E05] a failed save (disk full, serialization) must not kill training; the next milestone retries
                        utils.warning(f"Checkpoint save failed: {err}")
                just_loaded = False
                if telem is not None and (milestone_evaluation
                                          or milestone_checkpoint):
                    # Milestones already synced the device; refresh the
                    # heartbeat for free
                    telem.heartbeat(step=steps, status="running",
                                    steps_per_sec=rate_window.rate(),
                                    **_health_hb())
                if milestone_user_input:
                    code.interact(banner=f"Interactive prompt (step {steps}); "
                                  "Ctrl-D to resume", local={"state": state,
                                                             "engine": engine})
                if steps_limit is not None and steps >= steps_limit:
                    break
                # SIGUSR1: open a one-chunk jax.profiler window (live-run
                # debugging without restarting under --trace-dir); closed
                # right after the chunk it covers is drained below
                if profile_request[0] and profile_active is None:
                    profile_request[0] = False
                    if monitor is not None:
                        # SIGUSR1 is the live-debug hook: snapshot the
                        # flight recording alongside the profiler window
                        monitor.dump_blackbox(args.result_directory,
                                              reason="sigusr1")
                    if args.result_directory is None:
                        utils.warning("SIGUSR1 profiling needs "
                                      "'--result-directory'; ignored")
                    else:
                        pdir = args.result_directory / f"profile-{steps}"
                        try:
                            jax.profiler.start_trace(str(pdir))
                        except Exception as err:  # bmt: noqa[BMT-E05] jax.profiler raises backend-specific errors; a failed live-debug window is a warning
                            utils.warning(f"SIGUSR1 profiler window failed "
                                          f"to start ({err})")
                        else:
                            profile_active = (pdir, steps)
                            utils.info(f"SIGUSR1: profiling one chunk into "
                                       f"{str(pdir)!r}")
                # How many steps until the next milestone boundary — that many
                # can fuse into one compiled dispatch (identical trajectory;
                # `engine.train_multi*` is a lax.scan of the single step)
                def next_boundary(delta):
                    return (steps // delta + 1) * delta if delta > 0 else None
                bounds = [next_boundary(args.evaluation_delta),
                          next_boundary(args.checkpoint_delta),
                          next_boundary(args.user_input_delta),
                          steps_limit]
                horizon = min((b for b in bounds if b is not None),
                              default=steps + max(args.steps_per_program, 1))
                M = max(1, min(max(args.steps_per_program, 1), horizon - steps))
                # Per-step learning rates over the window (reference
                # `attack.py:748-751` semantics, evaluated per step)
                lrs = []
                for s in range(steps, steps + M):
                    new_lr = args.compute_new_learning_rate(s)
                    if new_lr is not None:
                        current_lr = new_lr
                    lrs.append(current_lr)
                # Sample the per-worker batches (host dataloader boundary,
                # reference `experiments/dataset.py:208-218`)
                S = cfg.nb_sampled
                k = cfg.nb_local_steps
                need = S * k
                # 'Training point count' is the value at loop entry, BEFORE each
                # step's increment (reference `attack.py:696, 844`)
                datapoints = datapoints_host
                # The four dispatch variants (indexed/host-staged × single/
                # fused) funnel into ONE call site so the telemetry timer
                # and the lazy FLOP counter bracket exactly what executes
                if use_device_data:
                    idx, flips = train_data.sample_indices(need * M)
                    idx = idx.reshape((M, S, k) + idx.shape[1:] if k > 1
                                      else (M, S) + idx.shape[1:])
                    flips = flips.reshape((M, S, k) + flips.shape[1:] if k > 1
                                          else (M, S) + flips.shape[1:])
                    batch = args.batch_size
                    if M == 1:
                        dispatch_fn = engine.train_step_indexed
                        dispatch_args = (state, jnp.asarray(idx[0]),
                                         jnp.asarray(flips[0]),
                                         jnp.float32(lrs[0]))
                    else:
                        dispatch_fn = engine.train_multi_indexed
                        dispatch_args = (state, jnp.asarray(idx),
                                         jnp.asarray(flips),
                                         jnp.asarray(lrs, jnp.float32))
                else:
                    xs, ys = zip(*(trainset.sample() for _ in range(need * M)))
                    xs = np.stack(xs)
                    ys = np.stack(ys)
                    batch = xs.shape[1]
                    shape = (M, S, k) if k > 1 else (M, S)
                    xs = xs.reshape(shape + xs.shape[1:])
                    ys = ys.reshape(shape + ys.shape[1:])
                    if M == 1:
                        dispatch_fn = step_fn
                        dispatch_args = (state, jnp.asarray(xs[0]),
                                         jnp.asarray(ys[0]),
                                         jnp.float32(lrs[0]))
                    else:
                        dispatch_fn = multi_fn
                        dispatch_args = (state, jnp.asarray(xs),
                                         jnp.asarray(ys),
                                         jnp.asarray(lrs, jnp.float32))
                if (telem is not None and args.telemetry_mfu
                        and mfu_flops is None):
                    # One throwaway compile of the program about to run
                    # (lowering only inspects avals — donation untouched);
                    # False = tried and failed, never retried
                    mfu_flops = obs_mod.logical_flops(
                        dispatch_fn, *dispatch_args) or False
                    if mfu_flops:
                        telem.event("flops_per_step", flops=mfu_flops)
                # --attribution window: trace exactly this chunk, and only
                # when its program shape is already warm (see the state
                # block above) — the window is deterministic: same step
                # range every run
                if (attrib_armed and attrib_window is None
                        and M in attrib_seen_m and profile_active is None):
                    adir = args.result_directory / "attribution-trace"
                    hlo_text, attrib_flops = lower_hlo_text(
                        dispatch_fn, dispatch_args)
                    try:
                        jax.profiler.start_trace(str(adir))
                    except Exception as err:  # bmt: noqa[BMT-E05] jax.profiler raises backend-specific errors; a failed attribution window is a warning
                        utils.warning(f"--attribution profiler window "
                                      f"failed to start ({err})")
                        attrib_armed = False
                    else:
                        attrib_window = (adir, M, hlo_text, attrib_flops)
                        utils.info(f"--attribution: tracing one {M}-step "
                                   f"chunk into {str(adir)!r}")
                # Telemetry sample: drain the pipeline (device->host barrier
                # on the pre-dispatch step counter), time this chunk's
                # dispatch-to-completion, then record gauges below. An
                # attribution window forces a sample so the device_step_ms
                # gauge covers the exact chunk the trace attributes.
                measure = telem is not None and (
                    steps_host >= next_sample_step
                    or attrib_window is not None)
                if measure:
                    step_timer.start(state.steps)
                state, metrics = dispatch_fn(*dispatch_args)
                steps_host += M
                datapoints_host += M * batch * cfg.nb_honests * k
                if telem is not None:
                    rate_window.update(steps_host)
                if measure:
                    device_s = step_timer.stop(state.steps)
                    device_ms = device_s * 1000.0 / M
                    rate = rate_window.rate()
                    rss = obs_mod.host_rss_mb()
                    telem.gauge("device_step_ms", device_ms, step=steps_host)
                    if rate is not None:
                        telem.gauge("steps_per_sec", rate, step=steps_host)
                    if rss is not None:
                        telem.gauge("host_rss_mb", rss, step=steps_host)
                    mfu_now = obs_mod.mfu(mfu_flops or None, rate, mfu_peak)
                    if mfu_now is not None:
                        telem.gauge("mfu", mfu_now, step=steps_host)
                    telem.heartbeat(step=steps_host, status="running",
                                    steps_per_sec=rate,
                                    device_step_ms=device_ms, rss_mb=rss,
                                    mfu=mfu_now, **_health_hb())
                    next_sample_step = steps_host + telem.interval
                attrib_seen_m.add(M)
                if attrib_window is not None:
                    # Close the --attribution window on the chunk it
                    # covered and attribute it right away
                    adir, a_steps, hlo_text, attrib_flops = attrib_window
                    attrib_window = None
                    attrib_armed = False
                    if not measure:
                        np.asarray(state.steps + 0)  # drain the chunk
                    try:
                        jax.profiler.stop_trace()
                    except Exception as err:  # bmt: noqa[BMT-E05] same contract as the SIGUSR1 window — the run outlives its profiler window
                        utils.warning(f"--attribution profiler window "
                                      f"failed to stop ({err})")
                    else:
                        attribute_window(adir, a_steps, hlo_text,
                                         attrib_flops,
                                         args.result_directory)
                if profile_active is not None:
                    # Close the SIGUSR1 window on the chunk it covered
                    np.asarray(state.steps + 0)  # drain the traced chunk
                    try:
                        jax.profiler.stop_trace()
                    except Exception as err:  # bmt: noqa[BMT-E05] same contract as start_trace — the run outlives its profiler window
                        utils.warning(f"SIGUSR1 profiler window failed to "
                                      f"stop ({err})")
                    pdir, pstep = profile_active
                    profile_active = None
                    if telem is not None:
                        telem.event("profiler_window", directory=str(pdir),
                                    from_step=pstep, to_step=steps_host)
                    utils.info(f"SIGUSR1: profiler window saved to "
                               f"{str(pdir)!r}")
                    # The live window auto-attributes too — the one-off
                    # `trace_opstats` archaeology becomes an artifact
                    # inside the window directory (throwaway re-lower of
                    # the chunk's program for the CPU scope join; on a
                    # stalled backend this degrades to op classes only)
                    hlo_text, pflops = lower_hlo_text(
                        dispatch_fn, dispatch_args)
                    attribute_window(pdir, steps_host - pstep, hlo_text,
                                     pflops, pdir)
                if chaos_blow is not None and steps_host > chaos_blow:
                    # Gradual-divergence chaos: multiplicative blow-up per
                    # chunk — several anomalous-but-finite steps precede
                    # the overflow (see the hook's comment above)
                    state = state._replace(
                        theta=state.theta * jnp.asarray(
                            chaos_blow_factor, state.theta.dtype))
                if chaos_nan is not None and steps_host > chaos_nan:
                    # Poison the freshly dispatched state (chaos hook): the
                    # health flag below must flip and trigger the rollback
                    if not chaos_nan_repeat:
                        chaos_nan = None
                    state = state._replace(theta=state.theta * jnp.asarray(
                        jnp.nan, state.theta.dtype))
                if health_enabled:
                    # max|theta| is +inf/NaN iff any coordinate is — a tiny
                    # derived scalar whose transfer rides the depth-2
                    # pipeline (checked at the NEXT loop top), so the
                    # divergence watchdog never stalls dispatch
                    pending_health.append(
                        jnp.isfinite(jnp.max(jnp.abs(state.theta))))
                if fd_study is not None:
                    # Transfer the PREVIOUS chunk's metrics now that this one
                    # is enqueued (its rows were buffered on device), then
                    # buffer this chunk's
                    flush_study()
                    pending_study.append(
                        (metrics, steps, datapoints, batch, M, rollbacks))
                else:
                    # No study file: the metrics transfer above would have
                    # throttled dispatch; transfer the previous chunk's tiny
                    # step counter instead, bounding host run-ahead (and the
                    # device memory pinned by in-flight input batches) to
                    # one chunk. `+ 0` derives a FRESH buffer — state.steps
                    # itself is donated (and deleted) by the next dispatch
                    if pending_sync:
                        np.asarray(pending_sync.pop())
                    pending_sync.append(state.steps + 0)

        finally:
            # Buffered study rows must reach disk on EVERY exit
            # path - normal completion, SIGINT latch, or an
            # exception escaping the loop (the pre-pipeline code
            # wrote rows synchronously per chunk) - and the result
            # descriptors must close/flush on those same paths
            flush_study()
            if results is not None:
                results.close()
    if args.trace_dir is not None:
        obs_mod.emit("profiler_trace_stop", directory=str(args.trace_dir))
        jax.profiler.stop_trace()
    if monitor is not None and monitor.steps > 0 and not diverged:
        # Every recorded run leaves a post-mortem, failed or not (the
        # blackbox of a completed run is its last-K health trace); a
        # diverged run keeps its divergence_giveup dump — the post-mortem
        # that matters must not be clobbered by a latest-wins rewrite
        monitor.dump_blackbox(args.result_directory, reason="run_end")
    if telem is not None:
        if suspicion is not None and suspicion.steps > 0:
            # Final forensics snapshot: who ended the run under suspicion
            # (the per-event timeline already has the rising/falling edges)
            telem.event("forensics_summary", **suspicion.summary())
        status = ("diverged" if diverged
                  else "interrupted" if exit_is_requested()
                  else "completed")
        if monitor is not None and monitor.steps > 0:
            # Final health snapshot: the run's standing anomaly state and
            # envelope estimates (the timeline has the per-edge events)
            telem.event("health_summary", **monitor.summary())
        telem.event("run_end", step=steps_host, status=status,
                    rollbacks=rollbacks, restarts=restart_count)
        telem.heartbeat(step=steps_host, status=status,
                        steps_per_sec=rate_window.rate(), **_health_hb())
        telem.close()
        obs_mod.deactivate()
    # A diverged run that spent its rollback budget is a failure: the Jobs
    # supervisor retries it (resuming from the last good checkpoint with a
    # fresh budget) instead of marking the directory done
    if diverged:
        return 1
    # A bounded run cut short by SIGINT/SIGTERM must not look successful:
    # the Jobs scheduler treats exit 0 as "complete" and would permanently
    # mark a truncated result directory as done (`utils/jobs.py`). Unlimited
    # runs (--nb-steps < 0) are legitimately stopped by a signal.
    if (exit_is_requested() and steps_limit is not None
            and int(state.steps) < steps_limit):
        return 130
    return 0


if __name__ == "__main__":
    sys.exit(main())
