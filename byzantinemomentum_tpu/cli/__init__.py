"""Command-line entry points (the reference's L4/L5 scripts as a package)."""
