"""Loss and criterion registries.

TPU-native redesign of reference `experiments/loss.py`: a loss is a pure
traceable function `(output, target, params_flat) -> scalar` (the reference's
exact signature, `loss.py:154-166`), wrapped in a `Loss` object composable
with `+` and `*` — used by the driver to add `--l1-regularize` /
`--l2-regularize` param-norm terms (reference `loss.py:168-207`,
`attack.py:534-538`).

The reference auto-registers every `torch.nn.modules.loss.*Loss` under its
lower-cased stripped name (`loss.py:87-109`); here the same names are
provided by explicit jnp implementations of the ones the experiment grids
and models actually use, plus the custom `l1`/`l2` param-norm losses
(`loss.py:31-67`).

A criterion maps `(output, target) -> f32[2] = [#correct, batch]`
(reference `loss.py:209-310`): built-ins `top-k` and `sigmoid`.
"""

import jax
import jax.numpy as jnp

from byzantinemomentum_tpu import utils

__all__ = ["Loss", "Criterion", "losses", "criteria", "register_loss",
           "register_criterion"]

# Registries: name -> builder(**kwargs) -> callable
losses = {}
criteria = {}


def register_loss(name, builder):
    if name in losses:
        utils.warning(f"Loss {name!r} registered twice; keeping the last")
    losses[name] = builder
    return builder


def register_criterion(name, builder):
    if name in criteria:
        utils.warning(f"Criterion {name!r} registered twice; keeping the last")
    criteria[name] = builder
    return builder


class Loss:
    """A composable loss: `Loss("nll") + 0.1 * Loss("l2")`
    (reference `experiments/loss.py:111-207`)."""

    def __init__(self, name_build, *args, **kwargs):
        if callable(name_build):
            self._fn = name_build
            self.name = getattr(name_build, "__name__", "custom")
        else:
            if name_build not in losses:
                utils.fatal_unavailable(losses, name_build, what="loss name")
            self._fn = losses[name_build](*args, **kwargs)
            self.name = name_build

    def __call__(self, output, target, params):
        return self._fn(output, target, params)

    def __add__(self, other):
        if not isinstance(other, Loss):
            return NotImplemented
        a, b = self._fn, other._fn
        out = Loss(lambda o, t, p: a(o, t, p) + b(o, t, p))
        out.name = f"{self.name}+{other.name}"
        return out

    def __mul__(self, factor):
        fn = self._fn
        out = Loss(lambda o, t, p: fn(o, t, p) * factor)
        out.name = f"{factor}*{self.name}"
        return out

    __rmul__ = __mul__

    def __repr__(self):
        return f"Loss({self.name!r})"


class Criterion:
    """An evaluation metric returning `[#correct, batch]`
    (reference `experiments/loss.py:209-310`)."""

    def __init__(self, name, **kwargs):
        if name not in criteria:
            utils.fatal_unavailable(criteria, name, what="criterion name")
        self._fn = criteria[name](**kwargs)
        self.name = name

    def __call__(self, output, target):
        return self._fn(output, target)

    def __repr__(self):
        return f"Criterion({self.name!r})"


# --------------------------------------------------------------------------- #
# Built-in losses

def _nll(**kw):
    """Negative log-likelihood over log-probability outputs, mean reduction
    (torch `NLLLoss` semantics — models in `models/simples.py` end with
    log_softmax, matching the reference's default pairing,
    `attack.py:134-137`)."""
    def loss(output, target, params):
        picked = jnp.take_along_axis(
            output, target.reshape(-1, 1).astype(jnp.int32), axis=1)
        return -jnp.mean(picked)
    return loss


def _crossentropy(**kw):
    """Cross-entropy over raw logits (torch `CrossEntropyLoss`)."""
    def loss(output, target, params):
        logp = output - jnp.max(output, axis=1, keepdims=True)
        logp = logp - jnp.log(jnp.sum(jnp.exp(logp), axis=1, keepdims=True))
        picked = jnp.take_along_axis(
            logp, target.reshape(-1, 1).astype(jnp.int32), axis=1)
        return -jnp.mean(picked)
    return loss


def _mse(**kw):
    def loss(output, target, params):
        return jnp.mean((output - target.reshape(output.shape)) ** 2)
    return loss


def _l1loss(**kw):
    """Torch `L1Loss` (mean absolute error) — distinct from the `l1`
    param-norm regularizer below, mirroring the reference where the custom
    `l1` replaces torch's in the registry (`loss.py:105-107`)."""
    def loss(output, target, params):
        return jnp.mean(jnp.abs(output - target.reshape(output.shape)))
    return loss


def _bce(**kw):
    """Torch `BCELoss` over probabilities in [0, 1]."""
    eps = 1e-12
    def loss(output, target, params):
        target = target.reshape(output.shape)
        return -jnp.mean(target * jnp.log(output + eps)
                         + (1.0 - target) * jnp.log(1.0 - output + eps))
    return loss


def _l1(**kw):
    """Param-norm L1 regularizer `‖θ‖₁` (reference `loss.py:31-40`)."""
    def loss(output, target, params):
        return jnp.sum(jnp.abs(params))
    return loss


def _l2(**kw):
    """Param-norm L2 regularizer `‖θ‖₂` (reference `loss.py:42-51` — note:
    the norm itself, not its square)."""
    def loss(output, target, params):
        return jnp.sqrt(jnp.sum(params * params))
    return loss


def _smoothl1(beta=1.0, **kw):
    """Torch `SmoothL1Loss` / Huber with delta=beta, mean reduction."""
    def loss(output, target, params):
        diff = jnp.abs(output - target.reshape(output.shape))
        return jnp.mean(jnp.where(diff < beta,
                                  0.5 * diff * diff / beta,
                                  diff - 0.5 * beta))
    return loss


def _kldiv(**kw):
    """Torch `KLDivLoss` (batchmean): inputs are log-probs, targets probs."""
    eps = 1e-12
    def loss(output, target, params):
        target = target.reshape(output.shape)
        return jnp.sum(target * (jnp.log(target + eps) - output)) / output.shape[0]
    return loss


def _hingeembedding(margin=1.0, **kw):
    """Torch `HingeEmbeddingLoss`: targets in {1, -1}."""
    def loss(output, target, params):
        target = target.reshape(output.shape)
        return jnp.mean(jnp.where(target > 0, output,
                                  jnp.maximum(0.0, margin - output)))
    return loss


def _bcewithlogits(**kw):
    """Torch `BCEWithLogitsLoss`: numerically-stable sigmoid + BCE."""
    def loss(output, target, params):
        t = target.reshape(output.shape)
        return jnp.mean(jnp.maximum(output, 0.0) - output * t
                        + jnp.log1p(jnp.exp(-jnp.abs(output))))
    return loss


def _poissonnll(log_input=True, full=False, eps=1e-8, **kw):
    """Torch `PoissonNLLLoss` (mean reduction, optional Stirling term)."""
    import math as _math
    def loss(output, target, params):
        t = target.reshape(output.shape)
        if log_input:
            out = jnp.exp(output) - t * output
        else:
            out = output - t * jnp.log(output + eps)
        if full:
            stirling = t * jnp.log(jnp.maximum(t, 1.0)) - t \
                + 0.5 * jnp.log(2.0 * _math.pi * jnp.maximum(t, 1.0))
            out = out + jnp.where(t > 1.0, stirling, 0.0)
        return jnp.mean(out)
    return loss


def _softmargin(**kw):
    """Torch `SoftMarginLoss`: targets in {1, -1}."""
    def loss(output, target, params):
        t = target.reshape(output.shape)
        return jnp.mean(jnp.log1p(jnp.exp(-t * output)))
    return loss


def _multimargin(p=1, margin=1.0, **kw):
    """Torch `MultiMarginLoss`: multi-class hinge over (N, C) logits."""
    def loss(output, target, params):
        c = output.shape[1]
        t = target.astype(jnp.int32).reshape(-1)
        x_y = jnp.take_along_axis(output, t[:, None], axis=1)
        m = jnp.maximum(0.0, margin - x_y + output)
        if p != 1:
            m = m ** p
        m = jnp.where(jax.nn.one_hot(t, c, dtype=bool), 0.0, m)
        return jnp.mean(jnp.sum(m, axis=1) / c)
    return loss


def _multilabelmargin(**kw):
    """Torch `MultiLabelMarginLoss`: target rows hold class indices
    terminated by -1."""
    def loss(output, target, params):
        n, c = output.shape
        t = target.astype(jnp.int32).reshape(n, -1)
        valid = jnp.cumprod(t >= 0, axis=1).astype(bool)
        tc = jnp.clip(t, 0)
        is_target = jnp.zeros((n, c), bool).at[
            jnp.arange(n)[:, None], tc].max(valid)
        x_t = jnp.take_along_axis(output, tc, axis=1)          # (n, k)
        hinge = jnp.maximum(0.0, 1.0 - x_t[:, :, None] + output[:, None, :])
        mask = valid[:, :, None] & ~is_target[:, None, :]
        return jnp.mean(jnp.sum(jnp.where(mask, hinge, 0.0), axis=(1, 2)) / c)
    return loss


def _multilabelsoftmargin(**kw):
    """Torch `MultiLabelSoftMarginLoss`: per-class BCE over {0,1} targets."""
    def loss(output, target, params):
        t = target.reshape(output.shape)
        per = t * jax.nn.log_sigmoid(output) \
            + (1.0 - t) * jax.nn.log_sigmoid(-output)
        return jnp.mean(-jnp.mean(per, axis=1))
    return loss


def _cosineembedding(margin=0.0, **kw):
    """Torch `CosineEmbeddingLoss`; `output` is the pair (x1, x2) — the
    reference registers this name but its two-input signature never fit the
    `(output, target)` call, so the pair-in-output convention is this repo's
    usable extension."""
    eps = 1e-8
    def loss(output, target, params):
        x1, x2 = output
        cos = jnp.sum(x1 * x2, axis=-1) / jnp.maximum(
            jnp.linalg.norm(x1, axis=-1) * jnp.linalg.norm(x2, axis=-1), eps)
        t = target.reshape(cos.shape)
        return jnp.mean(jnp.where(t > 0, 1.0 - cos,
                                  jnp.maximum(0.0, cos - margin)))
    return loss


def _marginranking(margin=0.0, **kw):
    """Torch `MarginRankingLoss`; `output` is the pair (x1, x2)."""
    def loss(output, target, params):
        x1, x2 = output
        t = target.reshape(x1.shape)
        return jnp.mean(jnp.maximum(0.0, -t * (x1 - x2) + margin))
    return loss


def _tripletmargin(margin=1.0, p=2, eps=1e-6, swap=False, **kw):
    """Torch `TripletMarginLoss`; `output` is the triple (anchor, pos, neg)."""
    def _pdist(a, b):
        return jnp.sum(jnp.abs(a - b + eps) ** p, axis=-1) ** (1.0 / p)
    def loss(output, target, params):
        a, pos, neg = output
        dp, dn = _pdist(a, pos), _pdist(a, neg)
        if swap:
            dn = jnp.minimum(dn, _pdist(pos, neg))
        return jnp.mean(jnp.maximum(0.0, dp - dn + margin))
    return loss


def _tripletmarginwithdistance(distance_function=None, margin=1.0,
                               swap=False, **kw):
    """Torch `TripletMarginWithDistanceLoss`; `output` is the triple
    (anchor, pos, neg), default distance = pairwise L2."""
    if distance_function is None:
        distance_function = lambda a, b: jnp.linalg.norm(a - b, axis=-1)
    def loss(output, target, params):
        a, pos, neg = output
        dp = distance_function(a, pos)
        dn = distance_function(a, neg)
        if swap:
            dn = jnp.minimum(dn, distance_function(pos, neg))
        return jnp.mean(jnp.maximum(0.0, dp - dn + margin))
    return loss


def _gaussiannll(full=False, eps=1e-6, **kw):
    """Torch `GaussianNLLLoss`; `output` is the pair (mean, var)."""
    import math as _math
    def loss(output, target, params):
        mu, var = output
        t = target.reshape(mu.shape)
        var = jnp.maximum(var, eps)
        out = 0.5 * (jnp.log(var) + (t - mu) ** 2 / var)
        if full:
            out = out + 0.5 * _math.log(2.0 * _math.pi)
        return jnp.mean(out)
    return loss


# Registered name-for-name with what the reference's auto-registration over
# `torch.nn.modules.loss` exposes (reference `experiments/loss.py:87-109`),
# with `l1`/`l2` replaced by the param-norm regularizers exactly as there.
# `ctc` is deliberately absent: `CTCLoss.forward` takes four arguments
# (log_probs, targets, input_lengths, target_lengths), so the name never fit
# the reference's own `(output, target)` wrapper either — it was registered
# but unusable. The multi-input losses (cosineembedding, marginranking,
# tripletmargin, gaussiannll) are in the same boat there; here they work by
# passing the input tuple as `output`.
register_loss("nll", _nll)
register_loss("crossentropy", _crossentropy)
register_loss("mse", _mse)
register_loss("l1loss", _l1loss)
register_loss("bce", _bce)
register_loss("smoothl1", _smoothl1)
register_loss("huber", _smoothl1)
register_loss("kldiv", _kldiv)
register_loss("hingeembedding", _hingeembedding)
register_loss("bcewithlogits", _bcewithlogits)
register_loss("poissonnll", _poissonnll)
register_loss("softmargin", _softmargin)
register_loss("multimargin", _multimargin)
register_loss("multilabelmargin", _multilabelmargin)
register_loss("multilabelsoftmargin", _multilabelsoftmargin)
register_loss("cosineembedding", _cosineembedding)
register_loss("marginranking", _marginranking)
register_loss("tripletmargin", _tripletmargin)
register_loss("tripletmarginwithdistance", _tripletmarginwithdistance)
register_loss("gaussiannll", _gaussiannll)
register_loss("l1", _l1)
register_loss("l2", _l2)


# --------------------------------------------------------------------------- #
# Built-in criteria

def _topk(k=1, **kw):
    """`top-k` criterion (reference `loss.py:213-234`)."""
    def criterion(output, target):
        k_eff = min(k, output.shape[1])
        _, idx = jax.lax.top_k(output, k_eff)
        correct = jnp.any(idx == target.reshape(-1, 1), axis=1)
        return jnp.array([jnp.sum(correct), output.shape[0]], jnp.float32)
    return criterion


def _sigmoid(**kw):
    """`sigmoid` criterion for binary outputs in [0, 1]
    (reference `loss.py:236-252`)."""
    def criterion(output, target):
        correct = jnp.abs(target.reshape(output.shape) - output) < 0.5
        return jnp.array([jnp.sum(correct), correct.size], jnp.float32)
    return criterion


register_criterion("top-k", _topk)
register_criterion("sigmoid", _sigmoid)
