"""Loss and criterion registries.

TPU-native redesign of reference `experiments/loss.py`: a loss is a pure
traceable function `(output, target, params_flat) -> scalar` (the reference's
exact signature, `loss.py:154-166`), wrapped in a `Loss` object composable
with `+` and `*` — used by the driver to add `--l1-regularize` /
`--l2-regularize` param-norm terms (reference `loss.py:168-207`,
`attack.py:534-538`).

The reference auto-registers every `torch.nn.modules.loss.*Loss` under its
lower-cased stripped name (`loss.py:87-109`); here the same names are
provided by explicit jnp implementations of the ones the experiment grids
and models actually use, plus the custom `l1`/`l2` param-norm losses
(`loss.py:31-67`).

A criterion maps `(output, target) -> f32[2] = [#correct, batch]`
(reference `loss.py:209-310`): built-ins `top-k` and `sigmoid`.
"""

import jax
import jax.numpy as jnp

from byzantinemomentum_tpu import utils

__all__ = ["Loss", "Criterion", "losses", "criteria", "register_loss",
           "register_criterion"]

# Registries: name -> builder(**kwargs) -> callable
losses = {}
criteria = {}


def register_loss(name, builder):
    if name in losses:
        utils.warning(f"Loss {name!r} registered twice; keeping the last")
    losses[name] = builder
    return builder


def register_criterion(name, builder):
    if name in criteria:
        utils.warning(f"Criterion {name!r} registered twice; keeping the last")
    criteria[name] = builder
    return builder


class Loss:
    """A composable loss: `Loss("nll") + 0.1 * Loss("l2")`
    (reference `experiments/loss.py:111-207`)."""

    def __init__(self, name_build, *args, **kwargs):
        if callable(name_build):
            self._fn = name_build
            self.name = getattr(name_build, "__name__", "custom")
        else:
            if name_build not in losses:
                utils.fatal_unavailable(losses, name_build, what="loss name")
            self._fn = losses[name_build](*args, **kwargs)
            self.name = name_build

    def __call__(self, output, target, params):
        return self._fn(output, target, params)

    def __add__(self, other):
        if not isinstance(other, Loss):
            return NotImplemented
        a, b = self._fn, other._fn
        out = Loss(lambda o, t, p: a(o, t, p) + b(o, t, p))
        out.name = f"{self.name}+{other.name}"
        return out

    def __mul__(self, factor):
        fn = self._fn
        out = Loss(lambda o, t, p: fn(o, t, p) * factor)
        out.name = f"{factor}*{self.name}"
        return out

    __rmul__ = __mul__

    def __repr__(self):
        return f"Loss({self.name!r})"


class Criterion:
    """An evaluation metric returning `[#correct, batch]`
    (reference `experiments/loss.py:209-310`)."""

    def __init__(self, name, **kwargs):
        if name not in criteria:
            utils.fatal_unavailable(criteria, name, what="criterion name")
        self._fn = criteria[name](**kwargs)
        self.name = name

    def __call__(self, output, target):
        return self._fn(output, target)

    def __repr__(self):
        return f"Criterion({self.name!r})"


# --------------------------------------------------------------------------- #
# Built-in losses

def _nll(**kw):
    """Negative log-likelihood over log-probability outputs, mean reduction
    (torch `NLLLoss` semantics — models in `models/simples.py` end with
    log_softmax, matching the reference's default pairing,
    `attack.py:134-137`)."""
    def loss(output, target, params):
        picked = jnp.take_along_axis(
            output, target.reshape(-1, 1).astype(jnp.int32), axis=1)
        return -jnp.mean(picked)
    return loss


def _crossentropy(**kw):
    """Cross-entropy over raw logits (torch `CrossEntropyLoss`)."""
    def loss(output, target, params):
        logp = output - jnp.max(output, axis=1, keepdims=True)
        logp = logp - jnp.log(jnp.sum(jnp.exp(logp), axis=1, keepdims=True))
        picked = jnp.take_along_axis(
            logp, target.reshape(-1, 1).astype(jnp.int32), axis=1)
        return -jnp.mean(picked)
    return loss


def _mse(**kw):
    def loss(output, target, params):
        return jnp.mean((output - target.reshape(output.shape)) ** 2)
    return loss


def _l1loss(**kw):
    """Torch `L1Loss` (mean absolute error) — distinct from the `l1`
    param-norm regularizer below, mirroring the reference where the custom
    `l1` replaces torch's in the registry (`loss.py:105-107`)."""
    def loss(output, target, params):
        return jnp.mean(jnp.abs(output - target.reshape(output.shape)))
    return loss


def _bce(**kw):
    """Torch `BCELoss` over probabilities in [0, 1]."""
    eps = 1e-12
    def loss(output, target, params):
        target = target.reshape(output.shape)
        return -jnp.mean(target * jnp.log(output + eps)
                         + (1.0 - target) * jnp.log(1.0 - output + eps))
    return loss


def _l1(**kw):
    """Param-norm L1 regularizer `‖θ‖₁` (reference `loss.py:31-40`)."""
    def loss(output, target, params):
        return jnp.sum(jnp.abs(params))
    return loss


def _l2(**kw):
    """Param-norm L2 regularizer `‖θ‖₂` (reference `loss.py:42-51` — note:
    the norm itself, not its square)."""
    def loss(output, target, params):
        return jnp.sqrt(jnp.sum(params * params))
    return loss


def _smoothl1(beta=1.0, **kw):
    """Torch `SmoothL1Loss` / Huber with delta=beta, mean reduction."""
    def loss(output, target, params):
        diff = jnp.abs(output - target.reshape(output.shape))
        return jnp.mean(jnp.where(diff < beta,
                                  0.5 * diff * diff / beta,
                                  diff - 0.5 * beta))
    return loss


def _kldiv(**kw):
    """Torch `KLDivLoss` (batchmean): inputs are log-probs, targets probs."""
    eps = 1e-12
    def loss(output, target, params):
        target = target.reshape(output.shape)
        return jnp.sum(target * (jnp.log(target + eps) - output)) / output.shape[0]
    return loss


def _hingeembedding(margin=1.0, **kw):
    """Torch `HingeEmbeddingLoss`: targets in {1, -1}."""
    def loss(output, target, params):
        target = target.reshape(output.shape)
        return jnp.mean(jnp.where(target > 0, output,
                                  jnp.maximum(0.0, margin - output)))
    return loss


register_loss("nll", _nll)
register_loss("crossentropy", _crossentropy)
register_loss("mse", _mse)
register_loss("l1loss", _l1loss)
register_loss("bce", _bce)
register_loss("smoothl1", _smoothl1)
register_loss("huber", _smoothl1)
register_loss("kldiv", _kldiv)
register_loss("hingeembedding", _hingeembedding)
register_loss("l1", _l1)
register_loss("l2", _l2)


# --------------------------------------------------------------------------- #
# Built-in criteria

def _topk(k=1, **kw):
    """`top-k` criterion (reference `loss.py:213-234`)."""
    def criterion(output, target):
        k_eff = min(k, output.shape[1])
        _, idx = jax.lax.top_k(output, k_eff)
        correct = jnp.any(idx == target.reshape(-1, 1), axis=1)
        return jnp.array([jnp.sum(correct), output.shape[0]], jnp.float32)
    return criterion


def _sigmoid(**kw):
    """`sigmoid` criterion for binary outputs in [0, 1]
    (reference `loss.py:236-252`)."""
    def criterion(output, target):
        correct = jnp.abs(target.reshape(output.shape) - output) < 0.5
        return jnp.array([jnp.sum(correct), correct.size], jnp.float32)
    return criterion


register_criterion("top-k", _topk)
register_criterion("sigmoid", _sigmoid)
