#!/usr/bin/env python3
"""Structured bench trajectory: per-cell steps/s across rounds.

`scripts/bench_compare.py` diffs two artifacts; this renders the whole
sequence — every `BENCH_r*.json` at the repo root (the harness wrapper
around one `bench.py` run per round), plus the working tree's
`BENCH_cells.json` (the machine-readable sibling `bench.py` now writes)
as the `current` row — into one table of steps/s per cell per round, so
"did the r5 packing win survive r7?" is one command instead of archaeology
over five JSON tails.

Alongside steps/s, the table renders a `gar ms/step` column out of each
round's phase-attribution artifact (`ATTRIB_r*.json` at the repo root —
the per-round copy of a run's `attribution.json` (obs/attrib), with the
working tree's `attribution.json` as `current`): the sum of the
`gar`/`gar_masked`/`gar_diag` phase budgets, i.e. the quantity the fused
Pallas GAR pipeline (PR 7) moves and the one a regression would regrow.
A round without an artifact shows `-`; an artifact from a non-TPU
backend renders with its backend noted, since phase budgets are only
comparable within one backend (the `bench_compare.py` attribution-gate
discipline).

The aggregation-service trajectory rides along the same way: per-round
`BENCH_serve_r*.json` load reports (`scripts/serve_loadgen.py`, with the
working tree's `BENCH_serve.json` as `current`) render serve p50/p99
latency and aggregations/s columns — the quantities the batching layer
moves and a serving regression would regrow; non-TPU load reports are
backend-noted like the attribution column. Per-round serve-attribution
artifacts (`ATTRIB_serve_r*.json`, `serve_loadgen.py --trace`, working
tree `ATTRIB_serve.json` as `current`) add the per-phase columns —
queue-wait / device / resolve p50 ms — so "which phase ate the p99"
reads off one table across rounds. Fleet-attribution artifacts
(`ATTRIB_serve_fleet_r*.json`, `--fleet --trace`, r19) add the two
JOINED hops only the cross-process splice can measure — shard-queue /
wire-resid p50 ms from the zipf scenario at the largest shard count —
so a convoy migrating between a shard's admission queue and the wire
reads off the same table.

Incomparability discipline (as `bench_compare.py`): a crashed round
(`rc != 0`, no parsed payload — e.g. the BENCH_r05 down-tunnel crash), a
`cpu-fallback` round, or a legacy artifact whose payload predates the
field being asked for is reported as INCOMPARABLE for that row/cell — the
table shows `-` and the script exits 0. The trajectory is information,
not a gate; gating lives in `bench_compare.py`.

Usage:
  python scripts/bench_history.py [--json] [--root DIR]
"""

import argparse
import json
import pathlib
import re
import sys

ROOT = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(ROOT / "scripts"))

from bench_compare import load_artifact, _rates  # noqa: E402

__all__ = ["collect_cluster", "collect_fleet", "collect_fleet_attrib",
           "collect_history", "collect_locks", "collect_metrics",
           "collect_serve", "collect_serve_attrib", "collect_tournament",
           "render_table", "main", "GAR_COLUMN", "CLUSTER_COLUMNS",
           "FLEET_COLUMNS", "FLEET_ATTRIB_COLUMNS", "LOCKS_COLUMNS",
           "METRICS_COLUMNS", "SERVE_COLUMNS", "SERVE_ATTRIB_COLUMNS",
           "TOURNAMENT_COLUMNS"]

_ROUND = re.compile(r"BENCH_r(\d+)\.json$")

# The phases whose per-step budgets sum into the `gar ms/step` column —
# the engine's aggregation scopes (`engine/step.py` named_scope names)
_GAR_PHASES = ("gar", "gar_masked", "gar_diag")
GAR_COLUMN = "gar ms/step"


def _gar_ms(root, label):
    """`(ms_per_step | None, backend | None)` for one round's
    phase-attribution artifact: `ATTRIB_r*.json` per round,
    `attribution.json` for the working tree's `current` row."""
    name = ("attribution.json" if label == "current"
            else f"ATTRIB_{label}.json")
    path = pathlib.Path(root) / name
    try:
        payload = json.loads(path.read_text())
    except (OSError, ValueError):
        return None, None
    if not isinstance(payload, dict) or payload.get("kind") != "attribution":
        return None, None
    phases = payload.get("phases") or {}
    total, seen = 0.0, False
    for phase in _GAR_PHASES:
        entry = phases.get(phase)
        if isinstance(entry, dict) and isinstance(entry.get("ms"),
                                                  (int, float)):
            total += float(entry["ms"])
            seen = True
    return (total if seen else None), payload.get("backend")


# Aggregation-service trajectory columns (`scripts/serve_loadgen.py`
# artifacts): open-loop latency percentiles + saturation throughput +
# the heterogeneous workload's distinct-compiled-program count (r10 —
# rounds before the two-axis ladder show `-`)
SERVE_COLUMNS = ("serve p50 ms", "serve p99 ms", "serve agg/s",
                 "serve compiles")


def _serve_stats(root, label):
    """`{p50, p99, rate, backend} | None` for one round's serve artifact:
    `BENCH_serve_r*.json` per round, the working tree's
    `BENCH_serve.json` for the `current` row."""
    name = ("BENCH_serve.json" if label == "current"
            else f"BENCH_serve_{label}.json")
    path = pathlib.Path(root) / name
    try:
        payload = json.loads(path.read_text())
    except (OSError, ValueError):
        return None
    if not isinstance(payload, dict) or payload.get("kind") != "serve":
        return None
    cells = payload.get("cells") or {}
    open_loop = cells.get("serve.open_loop") or {}
    batched = cells.get("serve.batched") or {}

    def num(d, key):
        v = d.get(key)
        return float(v) if isinstance(v, (int, float)) else None

    stats = {"p50": num(open_loop, "p50_ms"),
             "p99": num(open_loop, "p99_ms"),
             "rate": num(batched, "agg_per_sec"),
             "compiles": num(payload.get("compiles") or {},
                             "distinct_programs"),
             "backend": payload.get("backend")}
    if all(stats[k] is None for k in ("p50", "p99", "rate")):
        return None  # legacy/foreign payload with no renderable cell
    return stats


def collect_serve(root, labels):
    """{label: serve stats} over the rows `collect_history` produced
    (absent labels simply have no serve artifact — the instruments stay
    independent, the bench_compare discipline)."""
    return {label: stats for label in labels
            if (stats := _serve_stats(root, label)) is not None}


# Serve-attribution trajectory columns (`scripts/serve_loadgen.py
# --trace` artifacts, r13): the open-loop p50 of the three phases the
# serve optimizations move — queue wait (batching policy), device
# (kernels/buckets) and resolve (host-side unpack + suspicion) — from
# committed `ATTRIB_serve_r*.json` rounds
SERVE_ATTRIB_COLUMNS = ("queue-wait ms", "device ms", "resolve ms")


def _serve_attrib_stats(root, label):
    """`{queue, device, resolve, backend} | None` for one round's serve
    attribution: `ATTRIB_serve_r*.json` per round, the working tree's
    `ATTRIB_serve.json` for the `current` row."""
    name = ("ATTRIB_serve.json" if label == "current"
            else f"ATTRIB_serve_{label}.json")
    path = pathlib.Path(root) / name
    try:
        payload = json.loads(path.read_text())
    except (OSError, ValueError):
        return None
    if not isinstance(payload, dict) \
            or payload.get("kind") != "serve_attribution":
        return None
    phases = payload.get("phases") or {}

    def p50(phase):
        value = (phases.get(phase) or {}).get("p50_ms")
        return float(value) if isinstance(value, (int, float)) else None

    stats = {"queue": p50("queue"), "device": p50("device"),
             "resolve": p50("resolve"), "backend": payload.get("backend")}
    if all(stats[k] is None for k in ("queue", "device", "resolve")):
        return None  # legacy/foreign payload with no renderable phase
    return stats


def collect_serve_attrib(root, labels):
    """{label: serve-attribution stats} over the history rows
    (independent instrument, same discipline as `collect_serve`)."""
    return {label: stats for label in labels
            if (stats := _serve_attrib_stats(root, label)) is not None}


# Tournament (defense-loop) trajectory columns (`scripts/tournament.py`
# artifacts): the median time-to-quarantine over quarantine-on cells
# that actually evicted a Byzantine worker, and the honest-eviction
# total (the framing-resistance quantity — it must stay 0)
TOURNAMENT_COLUMNS = ("ttq median", "evicted honest")


def _tournament_stats(root, label):
    """`{ttq_median, evicted_honest, cells} | None` for one round's
    tournament scoreboard: `TOURNAMENT_r*.json` per round, the working
    tree's `TOURNAMENT.json` for the `current` row."""
    name = ("TOURNAMENT.json" if label == "current"
            else f"TOURNAMENT_{label}.json")
    path = pathlib.Path(root) / name
    try:
        payload = json.loads(path.read_text())
    except (OSError, ValueError):
        return None
    if (not isinstance(payload, dict)
            or payload.get("kind") != "tournament"):
        return None
    cells = payload.get("train_cells") or []
    ttqs = sorted(c["time_to_quarantine"] for c in cells
                  if c.get("quarantine")
                  and c.get("time_to_quarantine") is not None)
    summary = payload.get("summary") or {}
    return {
        "ttq_median": (ttqs[len(ttqs) // 2] if ttqs else None),
        "evicted_honest": summary.get("honest_evictions_total"),
        "cells": len(cells),
    }


def collect_tournament(root, labels):
    """{label: tournament stats} over the history rows (independent
    instrument, same discipline as `collect_serve`)."""
    return {label: stats for label in labels
            if (stats := _tournament_stats(root, label)) is not None}


# Multi-host trajectory columns (`scripts/cluster_smoke.py` artifacts):
# fleet size, lockstep cluster throughput, and the steps each chaos
# round's recovery re-executed (kill-to-restart distance — follows the
# fault plan, rendered for trend, gated nowhere)
CLUSTER_COLUMNS = ("hosts", "cluster steps/s", "recovery steps")


def _cluster_stats(root, label):
    """`{hosts, rate, recovery_steps, backend} | None` for one round's
    cluster artifact: `CLUSTER_r*.json` per round, the working tree's
    `CLUSTER.json` for the `current` row. Non-`ok` rounds (e.g. an
    `unavailable` runtime) are INCOMPARABLE for this instrument."""
    name = ("CLUSTER.json" if label == "current"
            else f"CLUSTER_{label}.json")
    path = pathlib.Path(root) / name
    try:
        payload = json.loads(path.read_text())
    except (OSError, ValueError):
        return None
    if not isinstance(payload, dict) or payload.get("kind") != "cluster":
        return None
    if payload.get("status") != "ok":
        return None
    rate = payload.get("steps_per_sec")
    return {"hosts": payload.get("hosts"),
            "rate": float(rate) if isinstance(rate, (int, float)) else None,
            "recovery_steps": (payload.get("recovery") or {}).get(
                "recovery_steps"),
            "backend": payload.get("backend")}


def collect_cluster(root, labels):
    """{label: cluster stats} over the history rows (independent
    instrument, same discipline as `collect_serve`)."""
    return {label: stats for label in labels
            if (stats := _cluster_stats(root, label)) is not None}


# Sharded-fleet trajectory columns (`scripts/serve_loadgen.py --fleet`
# artifacts, r16): the routed rotation-scenario throughput at the
# round's LARGEST shard count, that count, and whether every failover
# invariant held (parked-line recovery, survivor monotonicity, the
# re-warm bound) — 1 means the kill round corrupted nothing
FLEET_COLUMNS = ("fleet shards", "fleet agg/s", "fleet ok")


def _fleet_stats(root, label):
    """`{shards, rate, recovery_ok, backend} | None` for one round's
    fleet artifact: `BENCH_serve_fleet_r*.json` per round, the working
    tree's `BENCH_serve_fleet.json` for the `current` row. The rate is
    the rotation scenario at the largest shard count measured (the
    cross-shard-count INCOMPARABLE discipline lives in bench_compare;
    here the trajectory just names which count it renders)."""
    name = ("BENCH_serve_fleet.json" if label == "current"
            else f"BENCH_serve_fleet_{label}.json")
    path = pathlib.Path(root) / name
    try:
        payload = json.loads(path.read_text())
    except (OSError, ValueError):
        return None
    if not isinstance(payload, dict) \
            or payload.get("kind") != "serve_fleet":
        return None
    rotation = (payload.get("scenarios") or {}).get("rotation") or {}
    counts = sorted((c for c in rotation if c.isdigit()), key=int)
    if not counts:
        return None
    top = counts[-1]
    rate = (rotation[top] or {}).get("agg_per_sec")
    recovery = payload.get("recovery") or {}
    flags = [recovery.get(k) for k in ("parked_line_recovered",
                                       "survivor_monotonic",
                                       "rewarm_no_faster_than_fresh")]
    return {"shards": int(top),
            "rate": float(rate) if isinstance(rate, (int, float)) else None,
            "recovery_ok": (None if not any(isinstance(f, bool)
                                            for f in flags)
                            else all(f for f in flags
                                     if isinstance(f, bool))),
            "backend": payload.get("backend")}


def collect_fleet(root, labels):
    """{label: fleet stats} over the history rows (independent
    instrument, same discipline as `collect_serve`)."""
    return {label: stats for label in labels
            if (stats := _fleet_stats(root, label)) is not None}


# Fleet-attribution trajectory columns (`scripts/serve_loadgen.py
# --fleet --trace` artifacts, r19): the two JOINED hops only the
# cross-process splice can see — the shard's admission-queue wait and
# the wire residual (rtt minus everything the shard accounts for) —
# rendered from the zipf scenario at the largest shard count, where the
# hot-key convoy lives
FLEET_ATTRIB_COLUMNS = ("shard-queue ms", "wire-resid ms")


def _fleet_attrib_stats(root, label):
    """`{shard_queue, wire_residual, shards, backend} | None` for one
    round's fleet attribution: `ATTRIB_serve_fleet_r*.json` per round,
    the working tree's `ATTRIB_serve_fleet.json` for the `current`
    row."""
    name = ("ATTRIB_serve_fleet.json" if label == "current"
            else f"ATTRIB_serve_fleet_{label}.json")
    path = pathlib.Path(root) / name
    try:
        payload = json.loads(path.read_text())
    except (OSError, ValueError):
        return None
    if not isinstance(payload, dict) \
            or payload.get("kind") != "serve_fleet_attribution":
        return None
    zipf = (payload.get("scenarios") or {}).get("zipf") or {}
    counts = sorted((c for c in zipf if c.isdigit()), key=int)
    if not counts:
        return None
    top = counts[-1]
    hops = (zipf[top] or {}).get("hops") or {}

    def p50(hop):
        value = (hops.get(hop) or {}).get("p50_ms")
        return float(value) if isinstance(value, (int, float)) else None

    stats = {"shard_queue": p50("shard_queue"),
             "wire_residual": p50("wire_residual"),
             "shards": int(top), "backend": payload.get("backend")}
    if stats["shard_queue"] is None and stats["wire_residual"] is None:
        return None  # legacy/foreign payload with no renderable hop
    return stats


def collect_fleet_attrib(root, labels):
    """{label: fleet-attribution stats} over the history rows
    (independent instrument, same discipline as `collect_serve`)."""
    return {label: stats for label in labels
            if (stats := _fleet_attrib_stats(root, label)) is not None}


# Flight-recorder trajectory column (`scripts/health_overhead.py`
# artifacts): the paired on/off steps/s overhead of the in-jit health
# vector — the telemetry discipline's number, per round
HEALTH_COLUMNS = ("health ovh %",)


def _health_stats(root, label):
    """`{overhead_frac, backend} | None` for one round's health-overhead
    artifact: `BENCH_health_r*.json` per round, the working tree's
    `BENCH_health.json` for the `current` row. `--smoke` artifacts are
    INCOMPARABLE (harness proof, not a measurement)."""
    name = ("BENCH_health.json" if label == "current"
            else f"BENCH_health_{label}.json")
    path = pathlib.Path(root) / name
    try:
        payload = json.loads(path.read_text())
    except (OSError, ValueError):
        return None
    if not isinstance(payload, dict) \
            or payload.get("kind") != "health_overhead" \
            or payload.get("smoke"):
        return None
    overhead = payload.get("overhead_frac")
    if not isinstance(overhead, (int, float)):
        return None
    return {"overhead_frac": float(overhead),
            "backend": payload.get("backend")}


# Metrics-plane trajectory columns (`scripts/serve_loadgen.py
# --metrics-overhead` artifacts, r18): the paired on/off agg/s overhead
# of the serving registry and whether it held the committed bound —
# the metrics plane's own telemetry-discipline number, per round
METRICS_COLUMNS = ("metrics ovh %", "metrics ok")


def _metrics_stats(root, label):
    """`{overhead_frac, within_bound, backend} | None` for one round's
    metrics-overhead artifact: `BENCH_metrics_r*.json` per round, the
    working tree's `BENCH_metrics.json` for the `current` row.
    `--smoke` artifacts are INCOMPARABLE (harness proof, not a
    measurement)."""
    name = ("BENCH_metrics.json" if label == "current"
            else f"BENCH_metrics_{label}.json")
    path = pathlib.Path(root) / name
    try:
        payload = json.loads(path.read_text())
    except (OSError, ValueError):
        return None
    if not isinstance(payload, dict) \
            or payload.get("kind") != "metrics_overhead" \
            or payload.get("smoke"):
        return None
    overhead = payload.get("overhead_frac")
    if not isinstance(overhead, (int, float)):
        return None
    within = payload.get("within_bound")
    return {"overhead_frac": float(overhead),
            "within_bound": within if isinstance(within, bool) else None,
            "backend": payload.get("backend")}


def collect_metrics(root, labels):
    """{label: metrics-overhead stats} over the history rows (independent
    instrument, same discipline as `collect_serve`)."""
    return {label: stats for label in labels
            if (stats := _metrics_stats(root, label)) is not None}


def collect_health(root, labels):
    """{label: health-overhead stats} over the history rows (independent
    instrument, same discipline as `collect_serve`)."""
    return {label: stats for label in labels
            if (stats := _health_stats(root, label)) is not None}


LOCKS_COLUMNS = ("locks", "lock edges")


def _locks_stats(root, label):
    """`{locks, edges} | None` for one round's lock-hierarchy census:
    per-round rows read the tier artifact (`TESTS_{label}.json` ->
    `locks_tier`, recorded by `run_test_tiers.py` since r20); the
    `current` row reads the blessed census itself
    (`tests/goldens/locks.json`). Counts, not names — the table tracks
    whether the hierarchy is growing, the golden diff shows what."""
    root = pathlib.Path(root)
    if label == "current":
        path = root / "tests" / "goldens" / "locks.json"
        try:
            payload = json.loads(path.read_text())
        except (OSError, ValueError):
            return None
        names = payload.get("locks") if isinstance(payload, dict) else None
        edges = payload.get("edges") if isinstance(payload, dict) else None
        if not isinstance(names, list) or not isinstance(edges, list):
            return None
        return {"locks": len(names), "edges": len(edges)}
    path = root / f"TESTS_{label}.json"
    try:
        payload = json.loads(path.read_text())
    except (OSError, ValueError):
        return None
    tier = payload.get("locks_tier") if isinstance(payload, dict) else None
    if not isinstance(tier, dict):
        return None
    names, edges = tier.get("locks"), tier.get("edges")
    if not isinstance(names, int) or not isinstance(edges, int):
        return None
    return {"locks": names, "edges": edges}


def collect_locks(root, labels):
    """{label: lock-census counts} over the history rows (independent
    instrument, same discipline as `collect_serve`)."""
    return {label: stats for label in labels
            if (stats := _locks_stats(root, label)) is not None}


def collect_history(root=ROOT):
    """[(label, rates | None, reason | None, gar)] over every round
    artifact (sorted by round number) plus the working tree's
    `BENCH_cells.json` as `current` when present. `rates` is
    `bench_compare._rates`' flat `{cell: steps/s}` view; None marks an
    INCOMPARABLE round with its human-readable reason. `gar` is
    `(ms_per_step, backend) | None` from the round's attribution artifact
    (present even for INCOMPARABLE steps/s rounds — the instruments are
    independent)."""
    root = pathlib.Path(root)
    rows = []
    rounds = {}
    for path in root.glob("BENCH_r*.json"):
        m = _ROUND.search(path.name)
        if m:
            rounds[int(m.group(1))] = path
    # Rounds with only an attribution or serve artifact (e.g. a round
    # whose bench run never happened off-TPU) still get a row: the
    # instruments are independent and their columns must not wait for
    # steps/s
    for glob, pattern in (("ATTRIB_r*.json", r"ATTRIB_r(\d+)\.json$"),
                          ("BENCH_serve_r*.json",
                           r"BENCH_serve_r(\d+)\.json$"),
                          ("ATTRIB_serve_r*.json",
                           r"ATTRIB_serve_r(\d+)\.json$"),
                          ("TOURNAMENT_r*.json",
                           r"TOURNAMENT_r(\d+)\.json$"),
                          ("CLUSTER_r*.json", r"CLUSTER_r(\d+)\.json$"),
                          ("BENCH_health_r*.json",
                           r"BENCH_health_r(\d+)\.json$"),
                          ("BENCH_metrics_r*.json",
                           r"BENCH_metrics_r(\d+)\.json$"),
                          ("BENCH_serve_fleet_r*.json",
                           r"BENCH_serve_fleet_r(\d+)\.json$"),
                          ("ATTRIB_serve_fleet_r*.json",
                           r"ATTRIB_serve_fleet_r(\d+)\.json$")):
        for path in root.glob(glob):
            m = re.search(pattern, path.name)
            if m:
                rounds.setdefault(int(m.group(1)), None)
    labels = [f"r{number:02d}" for number in sorted(rounds)]
    paths = [rounds[number] for number in sorted(rounds)]
    current = root / "BENCH_cells.json"
    if (current.is_file() or (root / "attribution.json").is_file()
            or (root / "BENCH_serve.json").is_file()
            or (root / "ATTRIB_serve.json").is_file()
            or (root / "TOURNAMENT.json").is_file()
            or (root / "CLUSTER.json").is_file()
            or (root / "BENCH_health.json").is_file()
            or (root / "BENCH_metrics.json").is_file()
            or (root / "BENCH_serve_fleet.json").is_file()
            or (root / "ATTRIB_serve_fleet.json").is_file()):
        labels.append("current")
        paths.append(current if current.is_file() else None)
    for label, path in zip(labels, paths):
        if path is None:
            rates, reason = None, (f"{label}: no benchmark artifact "
                                   f"(attribution/serve only)")
        else:
            rates, reason = _load_rates(path)
        ms, backend = _gar_ms(root, label)
        gar = None if ms is None else (ms, backend)
        rows.append((label, rates, reason, gar))
    return rows


def _load_rates(path):
    try:
        payload, reason = load_artifact(path)
    except (OSError, json.JSONDecodeError) as err:
        return None, f"{path.name}: unreadable ({err})"
    if payload is None:
        return None, reason
    rates = _rates(payload)
    if not rates:
        return None, (f"{path.name}: legacy stdout-tail artifact with no "
                      f"parseable steps/s cells")
    return rates, None


def render_table(history, serve=None, tournament=None, cluster=None,
                 serve_attrib=None, health=None, fleet=None,
                 metrics=None, fleet_attrib=None, locks=None):
    """The trajectory as one text table: rounds as rows, every cell name
    seen in any comparable round as a column (columns a round lacks show
    `-`, e.g. the pre-`cells` legacy artifacts), plus the `gar ms/step`
    attribution column, the serve p50/p99/throughput columns, the serve
    per-phase attribution columns (queue-wait/device/resolve ms), the
    tournament defense-loop columns and the multi-host hosts/steps-per-s/
    recovery-steps columns when any round carries the matching
    artifact."""
    serve = serve or {}
    tournament = tournament or {}
    cluster = cluster or {}
    serve_attrib = serve_attrib or {}
    health = health or {}
    fleet = fleet or {}
    metrics = metrics or {}
    fleet_attrib = fleet_attrib or {}
    locks = locks or {}
    columns = []
    for _, rates, _, _ in history:
        for name in rates or ():
            if name not in columns:
                columns.append(name)
    any_gar = any(gar is not None for _, _, _, gar in history)
    if not columns and not any_gar and not serve and not tournament \
            and not cluster and not serve_attrib and not health \
            and not fleet and not metrics and not fleet_attrib \
            and not locks:
        lines = ["bench_history: no comparable rounds"]
        for label, _, reason, _ in history:
            lines.append(f"  {label}: INCOMPARABLE — {reason}")
        return "\n".join(lines)
    if any_gar:
        columns = columns + [GAR_COLUMN]
    if serve:
        columns = columns + list(SERVE_COLUMNS)
    if serve_attrib:
        columns = columns + list(SERVE_ATTRIB_COLUMNS)
    if tournament:
        columns = columns + list(TOURNAMENT_COLUMNS)
    if cluster:
        columns = columns + list(CLUSTER_COLUMNS)
    if health:
        columns = columns + list(HEALTH_COLUMNS)
    if fleet:
        columns = columns + list(FLEET_COLUMNS)
    if metrics:
        columns = columns + list(METRICS_COLUMNS)
    if fleet_attrib:
        columns = columns + list(FLEET_ATTRIB_COLUMNS)
    if locks:
        columns = columns + list(LOCKS_COLUMNS)
    label_w = max(len("round"), max(len(label) for label, _, _, _ in history))
    widths = [max(len(c), 9) for c in columns]
    header = "  ".join([f"{'round':<{label_w}}"]
                       + [f"{c:>{w}}" for c, w in zip(columns, widths)])
    lines = [header]
    notes = []
    for label, rates, reason, gar in history:
        if rates is None:
            notes.append(f"  {label}: INCOMPARABLE — {reason}")
        if gar is not None and gar[1] not in (None, "tpu"):
            # Phase budgets only compare within one backend — flag the
            # odd ones out instead of letting a CPU artifact masquerade
            # as a device regression/win
            notes.append(f"  {label}: gar ms/step from a "
                         f"backend={gar[1]} attribution artifact")
        row_serve = serve.get(label)
        if row_serve is not None and row_serve.get("backend") not in (
                None, "tpu"):
            notes.append(f"  {label}: serve columns from a "
                         f"backend={row_serve['backend']} load report")
        row_serve_attrib = serve_attrib.get(label)
        if row_serve_attrib is not None and row_serve_attrib.get(
                "backend") not in (None, "tpu"):
            notes.append(f"  {label}: serve phase columns from a "
                         f"backend={row_serve_attrib['backend']} trace "
                         f"report")
        row_tournament = tournament.get(label)
        row_cluster = cluster.get(label)
        row_health = health.get(label)
        row_fleet = fleet.get(label)
        row_metrics = metrics.get(label)
        row_fleet_attrib = fleet_attrib.get(label)
        row_locks = locks.get(label)
        if row_fleet_attrib is not None and row_fleet_attrib.get(
                "backend") not in (None, "tpu"):
            notes.append(f"  {label}: joined hop columns from a "
                         f"backend={row_fleet_attrib['backend']} fleet "
                         f"attribution")
        if row_metrics is not None and row_metrics.get("backend") not in (
                None, "tpu"):
            notes.append(f"  {label}: metrics overhead from a "
                         f"backend={row_metrics['backend']} measurement")
        if row_fleet is not None and row_fleet.get("backend") not in (
                None, "tpu"):
            notes.append(f"  {label}: fleet columns from a "
                         f"backend={row_fleet['backend']} fleet run")
        if row_health is not None and row_health.get("backend") not in (
                None, "tpu"):
            notes.append(f"  {label}: health overhead from a "
                         f"backend={row_health['backend']} measurement")
        if row_cluster is not None and row_cluster.get("backend") not in (
                None, "native"):
            # Cluster steps/s from the CPU-simulated fleet: comparable to
            # other CPU rounds only (the bench_compare cross-backend
            # discipline); flagged so a future native round stands out
            notes.append(f"  {label}: cluster columns from a "
                         f"backend={row_cluster['backend']} fleet")

        def cell(c, w):
            if c == GAR_COLUMN:
                return f"{gar[0]:>{w}.3f}" if gar is not None else f"{'-':>{w}}"
            if c in SERVE_COLUMNS:
                key = {"serve p50 ms": "p50", "serve p99 ms": "p99",
                       "serve agg/s": "rate",
                       "serve compiles": "compiles"}[c]
                value = None if row_serve is None else row_serve.get(key)
                if value is None:
                    return f"{'-':>{w}}"
                if key == "compiles":
                    return f"{int(value):>{w}d}"
                return f"{value:>{w}.3f}"
            if c in SERVE_ATTRIB_COLUMNS:
                key = {"queue-wait ms": "queue", "device ms": "device",
                       "resolve ms": "resolve"}[c]
                value = (None if row_serve_attrib is None
                         else row_serve_attrib.get(key))
                if value is None:
                    return f"{'-':>{w}}"
                return f"{value:>{w}.3f}"
            if c in TOURNAMENT_COLUMNS:
                key = {"ttq median": "ttq_median",
                       "evicted honest": "evicted_honest"}[c]
                value = (None if row_tournament is None
                         else row_tournament.get(key))
                if value is None:
                    return f"{'-':>{w}}"
                return f"{int(value):>{w}d}"
            if c in CLUSTER_COLUMNS:
                key = {"hosts": "hosts", "cluster steps/s": "rate",
                       "recovery steps": "recovery_steps"}[c]
                value = (None if row_cluster is None
                         else row_cluster.get(key))
                if value is None:
                    return f"{'-':>{w}}"
                if key == "rate":
                    return f"{value:>{w}.3f}"
                return f"{int(value):>{w}d}"
            if c in HEALTH_COLUMNS:
                if row_health is None:
                    return f"{'-':>{w}}"
                return f"{row_health['overhead_frac'] * 100:>{w}.2f}"
            if c in FLEET_COLUMNS:
                key = {"fleet shards": "shards", "fleet agg/s": "rate",
                       "fleet ok": "recovery_ok"}[c]
                value = None if row_fleet is None else row_fleet.get(key)
                if value is None:
                    return f"{'-':>{w}}"
                if key == "rate":
                    return f"{value:>{w}.3f}"
                return f"{int(value):>{w}d}"
            if c in METRICS_COLUMNS:
                if row_metrics is None:
                    return f"{'-':>{w}}"
                if c == "metrics ovh %":
                    return f"{row_metrics['overhead_frac'] * 100:>{w}.2f}"
                within = row_metrics.get("within_bound")
                if within is None:
                    return f"{'-':>{w}}"
                return f"{int(within):>{w}d}"
            if c in FLEET_ATTRIB_COLUMNS:
                key = {"shard-queue ms": "shard_queue",
                       "wire-resid ms": "wire_residual"}[c]
                value = (None if row_fleet_attrib is None
                         else row_fleet_attrib.get(key))
                if value is None:
                    return f"{'-':>{w}}"
                return f"{value:>{w}.3f}"
            if c in LOCKS_COLUMNS:
                key = {"locks": "locks", "lock edges": "edges"}[c]
                value = None if row_locks is None else row_locks.get(key)
                if value is None:
                    return f"{'-':>{w}}"
                return f"{int(value):>{w}d}"
            if rates is not None and c in rates:
                return f"{rates[c]:>{w}.3f}"
            return f"{'-':>{w}}"

        lines.append(f"{label:<{label_w}}  "
                     + "  ".join(cell(c, w) for c, w in zip(columns, widths)))
    if notes:
        lines.append("")
        lines.extend(notes)
    return "\n".join(lines)


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="bench_history",
        description="Per-cell steps/s trajectory over every BENCH_r*.json "
                    "round (informational: always exits 0 unless the "
                    "arguments are wrong)")
    parser.add_argument("--root", default=str(ROOT),
                        help="directory holding the BENCH_r*.json "
                             "artifacts (default: the repo root)")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable output")
    args = parser.parse_args(argv)

    history = collect_history(pathlib.Path(args.root))
    if not history:
        print("bench_history: no BENCH_r*.json artifacts found")
        return 0
    serve = collect_serve(pathlib.Path(args.root),
                          [label for label, *_ in history])
    serve_attrib = collect_serve_attrib(pathlib.Path(args.root),
                                        [label for label, *_ in history])
    tournament = collect_tournament(pathlib.Path(args.root),
                                    [label for label, *_ in history])
    cluster = collect_cluster(pathlib.Path(args.root),
                              [label for label, *_ in history])
    health = collect_health(pathlib.Path(args.root),
                            [label for label, *_ in history])
    fleet = collect_fleet(pathlib.Path(args.root),
                          [label for label, *_ in history])
    metrics = collect_metrics(pathlib.Path(args.root),
                              [label for label, *_ in history])
    fleet_attrib = collect_fleet_attrib(pathlib.Path(args.root),
                                        [label for label, *_ in history])
    locks = collect_locks(pathlib.Path(args.root),
                          [label for label, *_ in history])
    if args.json:
        print(json.dumps([
            {"round": label, "rates": rates, "reason": reason,
             "gar_ms_per_step": None if gar is None else gar[0],
             "gar_backend": None if gar is None else gar[1],
             "serve": serve.get(label),
             "serve_attrib": serve_attrib.get(label),
             "tournament": tournament.get(label),
             "cluster": cluster.get(label),
             "health": health.get(label),
             "fleet": fleet.get(label),
             "metrics": metrics.get(label),
             "fleet_attrib": fleet_attrib.get(label),
             "locks": locks.get(label)}
            for label, rates, reason, gar in history], indent=2))
        return 0
    print(render_table(history, serve, tournament, cluster, serve_attrib,
                       health, fleet, metrics, fleet_attrib, locks))
    return 0


if __name__ == "__main__":
    sys.exit(main())
