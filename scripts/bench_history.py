#!/usr/bin/env python3
"""Structured bench trajectory: per-cell steps/s across rounds.

`scripts/bench_compare.py` diffs two artifacts; this renders the whole
sequence — every `BENCH_r*.json` at the repo root (the harness wrapper
around one `bench.py` run per round), plus the working tree's
`BENCH_cells.json` (the machine-readable sibling `bench.py` now writes)
as the `current` row — into one table of steps/s per cell per round, so
"did the r5 packing win survive r7?" is one command instead of archaeology
over five JSON tails.

Incomparability discipline (as `bench_compare.py`): a crashed round
(`rc != 0`, no parsed payload — e.g. the BENCH_r05 down-tunnel crash), a
`cpu-fallback` round, or a legacy artifact whose payload predates the
field being asked for is reported as INCOMPARABLE for that row/cell — the
table shows `-` and the script exits 0. The trajectory is information,
not a gate; gating lives in `bench_compare.py`.

Usage:
  python scripts/bench_history.py [--json] [--root DIR]
"""

import argparse
import json
import pathlib
import re
import sys

ROOT = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(ROOT / "scripts"))

from bench_compare import load_artifact, _rates  # noqa: E402

__all__ = ["collect_history", "render_table", "main"]

_ROUND = re.compile(r"BENCH_r(\d+)\.json$")


def collect_history(root=ROOT):
    """[(label, rates | None, reason | None)] over every round artifact
    (sorted by round number) plus the working tree's `BENCH_cells.json`
    as `current` when present. `rates` is `bench_compare._rates`' flat
    `{cell: steps/s}` view; None marks an INCOMPARABLE round with its
    human-readable reason."""
    root = pathlib.Path(root)
    rows = []
    rounds = []
    for path in root.glob("BENCH_r*.json"):
        m = _ROUND.search(path.name)
        if m:
            rounds.append((int(m.group(1)), path))
    for number, path in sorted(rounds):
        rows.append((f"r{number:02d}",) + _load_rates(path))
    current = root / "BENCH_cells.json"
    if current.is_file():
        rows.append(("current",) + _load_rates(current))
    return rows


def _load_rates(path):
    try:
        payload, reason = load_artifact(path)
    except (OSError, json.JSONDecodeError) as err:
        return None, f"{path.name}: unreadable ({err})"
    if payload is None:
        return None, reason
    rates = _rates(payload)
    if not rates:
        return None, (f"{path.name}: legacy stdout-tail artifact with no "
                      f"parseable steps/s cells")
    return rates, None


def render_table(history):
    """The trajectory as one text table: rounds as rows, every cell name
    seen in any comparable round as a column (columns a round lacks show
    `-`, e.g. the pre-`cells` legacy artifacts)."""
    columns = []
    for _, rates, _ in history:
        for name in rates or ():
            if name not in columns:
                columns.append(name)
    if not columns:
        lines = ["bench_history: no comparable rounds"]
        for label, _, reason in history:
            lines.append(f"  {label}: INCOMPARABLE — {reason}")
        return "\n".join(lines)
    label_w = max(len("round"), max(len(label) for label, _, _ in history))
    widths = [max(len(c), 9) for c in columns]
    header = "  ".join([f"{'round':<{label_w}}"]
                       + [f"{c:>{w}}" for c, w in zip(columns, widths)])
    lines = [header]
    notes = []
    for label, rates, reason in history:
        if rates is None:
            lines.append(f"{label:<{label_w}}  "
                         + "  ".join(f"{'-':>{w}}" for w in widths))
            notes.append(f"  {label}: INCOMPARABLE — {reason}")
            continue
        cells = [(f"{rates[c]:>{w}.3f}" if c in rates else f"{'-':>{w}}")
                 for c, w in zip(columns, widths)]
        lines.append(f"{label:<{label_w}}  " + "  ".join(cells))
    if notes:
        lines.append("")
        lines.extend(notes)
    return "\n".join(lines)


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="bench_history",
        description="Per-cell steps/s trajectory over every BENCH_r*.json "
                    "round (informational: always exits 0 unless the "
                    "arguments are wrong)")
    parser.add_argument("--root", default=str(ROOT),
                        help="directory holding the BENCH_r*.json "
                             "artifacts (default: the repo root)")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable output")
    args = parser.parse_args(argv)

    history = collect_history(pathlib.Path(args.root))
    if not history:
        print("bench_history: no BENCH_r*.json artifacts found")
        return 0
    if args.json:
        print(json.dumps([
            {"round": label, "rates": rates, "reason": reason}
            for label, rates, reason in history], indent=2))
        return 0
    print(render_table(history))
    return 0


if __name__ == "__main__":
    sys.exit(main())
