#!/usr/bin/env python3
"""Run the test suite tier by tier and record a verifiable artifact.

Writes `TESTS_r{N}.json` at the repo root: the default tier in one pytest
invocation, then the slow tier (`--runslow -m slow`) SHARDED BY FILE with
per-shard pass counts and wall times — the build host has one CPU core, so
a single `--runslow` run exceeds any reasonable review window (VERDICT r4
item 3); per-file shards keep each run bounded and the artifact shows all
of them green at the recorded HEAD.

Usage: python scripts/run_test_tiers.py --round 5
"""

import argparse
import json
import pathlib
import re
import subprocess
import sys
import time

ROOT = pathlib.Path(__file__).resolve().parent.parent

# Token-wise parse: a summary line may lack any given token (e.g. an
# all-fail shard prints only "3 failed in ..."), so match each count
# independently instead of one positional pattern
_TOKEN = re.compile(r"(\d+) (passed|failed|skipped|error(?:s)?)")


def run_pytest(args):
    start = time.monotonic()
    proc = subprocess.run(
        [sys.executable, "-m", "pytest", "-q", "--tb=line", *args],
        cwd=ROOT, capture_output=True, text=True)
    elapsed = time.monotonic() - start
    counts = {"passed": 0, "failed": 0, "skipped": 0, "errors": 0}
    for line in reversed(proc.stdout.splitlines()):
        tokens = _TOKEN.findall(line)
        if tokens:
            for num, kind in tokens:
                key = "errors" if kind.startswith("error") else kind
                counts[key] = int(num)
            break
    else:
        if "no tests ran" not in proc.stdout:
            counts["errors"] = max(counts["errors"], proc.returncode != 0)
    counts["seconds"] = round(elapsed, 1)
    counts["returncode"] = proc.returncode
    if proc.returncode not in (0, 5):  # 5 = no tests collected (empty shard)
        counts["tail"] = proc.stdout.splitlines()[-12:]
    return counts


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--round", type=int, required=True)
    parser.add_argument("--out", type=str, default=None)
    args = parser.parse_args()

    head = subprocess.run(["git", "rev-parse", "HEAD"], cwd=ROOT,
                          capture_output=True, text=True).stdout.strip()

    print("default tier ...", flush=True)
    default = run_pytest(["tests/"])
    print(f"  {default}", flush=True)

    shards = {}
    for path in sorted((ROOT / "tests").glob("test_*.py")):
        print(f"slow tier: {path.name} ...", flush=True)
        res = run_pytest([f"tests/{path.name}", "--runslow", "-m", "slow"])
        if res["returncode"] == 5:  # file has no slow tests
            continue
        shards[path.name] = res
        print(f"  {res}", flush=True)

    slow_total = {
        "passed": sum(s["passed"] for s in shards.values()),
        "failed": sum(s["failed"] for s in shards.values()),
        "skipped": sum(s["skipped"] for s in shards.values()),
        "seconds": round(sum(s["seconds"] for s in shards.values()), 1),
    }
    out = {
        "round": args.round,
        "git_head": head,
        "host": "1-core TPU build host (slow tier sharded by file "
                "because one --runslow run exceeds a review window)",
        "default_tier": default,
        "slow_tier_total": slow_total,
        "slow_tier_shards": shards,
        "green": bool(default["failed"] == 0 and default["errors"] == 0
                      and default["returncode"] == 0
                      and slow_total["failed"] == 0
                      and all(s["returncode"] == 0 for s in shards.values())),
    }
    path = pathlib.Path(args.out) if args.out else (
        ROOT / f"TESTS_r{args.round:02d}.json")
    path.write_text(json.dumps(out, indent=2) + "\n")
    print(json.dumps({k: out[k] for k in
                      ("round", "git_head", "green")}
                     | {"default": default["passed"],
                        "slow": slow_total["passed"]}))


if __name__ == "__main__":
    main()
