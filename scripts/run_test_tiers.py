#!/usr/bin/env python3
"""Run the test suite tier by tier and record a verifiable artifact.

Writes `TESTS_r{N}.json` at the repo root: the default tier in one pytest
invocation, then the slow tier (`--runslow -m slow`) SHARDED BY FILE with
per-shard pass counts and wall times — the build host has one CPU core, so
a single `--runslow` run exceeds any reasonable review window (VERDICT r4
item 3); per-file shards keep each run bounded and the artifact shows all
of them green at the recorded HEAD.

The harness eats its own dog food (PR 3): before anything else it runs the
`python -m byzantinemomentum_tpu.obs --selfcheck` smoke, and it records its
own telemetry — one span per tier/shard with the pass counts, the obs
recorder writing `TESTS_r{N}.telemetry.jsonl` next to the artifact — so a
CI log reader gets the same timeline format as a training run.

Usage: python scripts/run_test_tiers.py --round 5
"""

import argparse
import json
import os
import pathlib
import re
import subprocess
import sys
import time

ROOT = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(ROOT))

from byzantinemomentum_tpu.obs import Telemetry  # noqa: E402

# Token-wise parse: a summary line may lack any given token (e.g. an
# all-fail shard prints only "3 failed in ..."), so match each count
# independently instead of one positional pattern
_TOKEN = re.compile(r"(\d+) (passed|failed|skipped|error(?:s)?)")


def run_pytest(args, env=None):
    start = time.monotonic()
    proc = subprocess.run(
        [sys.executable, "-m", "pytest", "-q", "--tb=line", *args],
        cwd=ROOT, capture_output=True, text=True,
        env={**os.environ, **env} if env else None)
    elapsed = time.monotonic() - start
    counts = {"passed": 0, "failed": 0, "skipped": 0, "errors": 0}
    for line in reversed(proc.stdout.splitlines()):
        tokens = _TOKEN.findall(line)
        if tokens:
            for num, kind in tokens:
                key = "errors" if kind.startswith("error") else kind
                counts[key] = int(num)
            break
    else:
        if "no tests ran" not in proc.stdout:
            counts["errors"] = max(counts["errors"], proc.returncode != 0)
    counts["seconds"] = round(elapsed, 1)
    counts["returncode"] = proc.returncode
    if proc.returncode not in (0, 5):  # 5 = no tests collected (empty shard)
        counts["tail"] = proc.stdout.splitlines()[-12:]
    return counts


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--round", type=int, required=True)
    parser.add_argument("--out", type=str, default=None)
    args = parser.parse_args()

    head = subprocess.run(["git", "rev-parse", "HEAD"], cwd=ROOT,
                          capture_output=True, text=True).stdout.strip()

    telemetry = Telemetry(ROOT, filename=f"TESTS_r{args.round:02d}"
                                         ".telemetry.jsonl")
    telemetry.event("run_start", round=args.round, git_head=head)

    # Observability smoke: the obs stack must hold its own invariants
    # before its telemetry of the tiers below means anything. The
    # selfcheck includes the attribution pipeline (PR 6) and prints its
    # artifact as one `attribution: {...}` line — recorded here so the
    # per-tier telemetry carries the per-phase numbers the smoke measured.
    print("obs selfcheck ...", flush=True)
    selfcheck = subprocess.run(
        [sys.executable, "-m", "byzantinemomentum_tpu.obs", "--selfcheck"],
        cwd=ROOT, capture_output=True, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    obs_selfcheck = {"returncode": selfcheck.returncode}
    attribution = None
    health = None
    metrics = None
    for line in selfcheck.stdout.splitlines():
        if line.startswith("attribution: "):
            try:
                attribution = json.loads(line[len("attribution: "):])
            except ValueError:
                pass  # a torn artifact line is a selfcheck bug, not ours
        elif line.startswith("health: "):
            # The flight-recorder phase (PR 15): detection lags for the
            # planted NaN burst / variance collapse, clean-stream false
            # positives (must be 0), blackbox ring bound
            try:
                health = json.loads(line[len("health: "):])
            except ValueError:
                pass
        elif line.startswith("metrics: "):
            # The metrics-plane phase (PR 18): scrape roundtrip, N-shard
            # merge parity quantiles, per-bump cost sanity
            try:
                metrics = json.loads(line[len("metrics: "):])
            except ValueError:
                pass
    if attribution is not None:
        obs_selfcheck["attribution"] = attribution
    if health is not None:
        obs_selfcheck["health"] = health
    if metrics is not None:
        obs_selfcheck["metrics"] = metrics
    if selfcheck.returncode != 0:
        obs_selfcheck["tail"] = (selfcheck.stdout
                                 + selfcheck.stderr).splitlines()[-12:]
    telemetry.event("obs_selfcheck", returncode=selfcheck.returncode,
                    attribution=attribution, health=health)
    print(f"  {obs_selfcheck}", flush=True)

    # Bench-regression tooling smoke: the comparator must run over the
    # repo's latest artifact pair (an enormous tolerance — this smoke
    # proves the tool, the real threshold is the caller's choice; crashed
    # or cpu-fallback artifacts must come back INCOMPARABLE, exit 0)
    print("bench compare smoke ...", flush=True)
    bench_cmp = subprocess.run(
        [sys.executable, "scripts/bench_compare.py", "--tolerance", "1e9"],
        cwd=ROOT, capture_output=True, text=True)
    bench_compare = {"returncode": bench_cmp.returncode,
                     "head": bench_cmp.stdout.splitlines()[:2]}
    telemetry.event("bench_compare_smoke", returncode=bench_cmp.returncode)
    print(f"  {bench_compare}", flush=True)

    # Lint tier (PR 5, grown in PR 9/14): jaxlint (BMT-E rules incl. the
    # dead-noqa detector) AND the BMT-T concurrency rules over the
    # package + scripts — the source half of the static gate, with its
    # own green bit. --json so the artifact records the per-family hit
    # counts (t_rule_hits MUST be 0: the thread surface is contract-
    # clean), plus the schedule smoke: the interleaving harness proves
    # the planted serve-counter lost-update is FOUND and the fixed
    # stats-lock pattern is schedule-clean (exhaustive 2-thread
    # exploration, well under the 10 s budget).
    print("lint tier ...", flush=True)
    with telemetry.span("tier_lint"):
        lint_proc = subprocess.run(
            [sys.executable, "-m", "byzantinemomentum_tpu.analysis",
             "byzantinemomentum_tpu", "scripts", "--json"],
            cwd=ROOT, capture_output=True, text=True)
        sched_proc = subprocess.run(
            [sys.executable, "-m", "byzantinemomentum_tpu.analysis",
             "--schedule-smoke"],
            cwd=ROOT, capture_output=True, text=True)
    lint_tier = {"returncode": lint_proc.returncode
                 or sched_proc.returncode,
                 "tail": lint_proc.stdout.splitlines()[-4:]}
    try:
        counts = json.loads(lint_proc.stdout).get("counts", {})
        lint_tier["t_rule_hits"] = sum(
            v for k, v in counts.items() if k.startswith("BMT-T"))
        lint_tier["e_rule_hits"] = sum(
            v for k, v in counts.items() if k.startswith("BMT-E"))
    except ValueError:
        pass  # non-JSON output means the CLI crashed; returncode covers it
    schedule_smoke = None
    for line in sched_proc.stdout.splitlines():
        if line.startswith("schedule: "):
            try:
                schedule_smoke = json.loads(line[len("schedule: "):])
            except ValueError:
                continue
    if schedule_smoke is not None:
        lint_tier["schedule_smoke"] = schedule_smoke
    telemetry.event("lint_tier", returncode=lint_tier["returncode"],
                    t_rule_hits=lint_tier.get("t_rule_hits"),
                    schedule_smoke=schedule_smoke)
    print(f"  {lint_tier}", flush=True)

    # Locks tier (PR 20): the whole-program BMT-L sweep — the
    # interprocedural lock-order graph must carry zero unannotated
    # violations AND match the blessed hierarchy
    # (tests/goldens/locks.json) exactly; drift fails until re-blessed
    # with the change that caused it. Own green bit + telemetry with
    # the edge/cycle census.
    print("locks tier ...", flush=True)
    with telemetry.span("tier_locks"):
        locks_proc = subprocess.run(
            [sys.executable, "-m", "byzantinemomentum_tpu.analysis",
             "--check-locks", "--json"],
            cwd=ROOT, capture_output=True, text=True)
    locks_tier = {"returncode": locks_proc.returncode,
                  "tail": locks_proc.stdout.splitlines()[-4:]}
    try:
        locks_report = json.loads(locks_proc.stdout)
        locks_tier.update(
            status=locks_report.get("status"),
            locks=locks_report.get("locks"),
            edges=locks_report.get("edges"),
            cycles=locks_report.get("cycles"),
            l_rule_hits=len(locks_report.get("violations", ())),
            suppressed=locks_report.get("suppressed"))
        locks_tier.pop("tail", None)
    except ValueError:
        pass  # non-JSON output means the CLI crashed; returncode covers it
    telemetry.event("locks_tier", returncode=locks_tier["returncode"],
                    status=locks_tier.get("status"),
                    edges=locks_tier.get("edges"),
                    cycles=locks_tier.get("cycles"),
                    l_rule_hits=locks_tier.get("l_rule_hits"))
    print(f"  {locks_tier}", flush=True)

    # Lattice tier (PR 9): the builder-derived lowering-contract gate —
    # StableHLO fingerprints over the whole program lattice (GAR cells,
    # virtual-mesh sharded cells, serve cells, the donated update) PLUS
    # the BMT-H structural lint (collective census, no worker-matrix
    # all-gather, donation honored, no f64, no host callbacks) over every
    # lowered cell. Own green bit + telemetry span with the cell count.
    print("lattice tier ...", flush=True)
    with telemetry.span("tier_lattice"):
        lattice_proc = subprocess.run(
            [sys.executable, "-m", "byzantinemomentum_tpu.analysis",
             "--check-lowerings"],
            cwd=ROOT, capture_output=True, text=True)
    cells_checked = None
    for line in lattice_proc.stdout.splitlines():
        m = re.search(r"lowerings: \w+ \((\d+) cells\)", line)
        if m:
            cells_checked = int(m.group(1))
    lattice_tier = {"returncode": lattice_proc.returncode,
                    "cells": cells_checked,
                    "tail": lattice_proc.stdout.splitlines()[-4:]}
    telemetry.event("lattice_tier", returncode=lattice_proc.returncode,
                    cells=cells_checked)
    print(f"  {lattice_tier}", flush=True)

    print("default tier ...", flush=True)
    with telemetry.span("tier_default"):
        default = run_pytest(["tests/"])
    telemetry.event("tier_result", tier="default", **default)
    telemetry.counter("tests_passed", default["passed"])
    telemetry.counter("tests_failed", default["failed"])
    print(f"  {default}", flush=True)

    # No-Pallas tier (PR 7): the kernel-adjacent files rerun with
    # `BMT_NO_PALLAS=1`, so CI exercises BOTH the fused kernels (the
    # interpret-mode tests in the default tier force the kernel paths)
    # and the jnp fallback paths every run — before this tier the
    # fallbacks were only covered incidentally off-TPU
    print("no-pallas tier ...", flush=True)
    with telemetry.span("tier_nopallas"):
        nopallas = run_pytest(
            ["tests/test_pallas.py", "tests/test_gars.py",
             "tests/test_diag.py", "tests/test_faults.py"],
            env={"BMT_NO_PALLAS": "1"})
    telemetry.event("tier_result", tier="nopallas", **nopallas)
    telemetry.counter("tests_passed", nopallas["passed"])
    telemetry.counter("tests_failed", nopallas["failed"])
    print(f"  {nopallas}", flush=True)

    # Serve tier (PR 8): the aggregation-service selfcheck (warm-loop
    # zero-recompile budget, suspicion path, socket round-trip) plus the
    # load generator's smoke path — the serving substrate gets its own
    # green bit and telemetry span like every other subsystem
    print("serve tier ...", flush=True)
    with telemetry.span("tier_serve"):
        serve_check = subprocess.run(
            [sys.executable, "-m", "byzantinemomentum_tpu.serve",
             "--selfcheck"],
            cwd=ROOT, capture_output=True, text=True,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        serve_load = subprocess.run(
            [sys.executable, "scripts/serve_loadgen.py", "--smoke"],
            cwd=ROOT, capture_output=True, text=True,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
    serve_tier = {"selfcheck": serve_check.returncode,
                  "loadgen": serve_load.returncode,
                  "returncode": serve_check.returncode
                  or serve_load.returncode}
    # The selfcheck's trace phase (PR 13): span-tiling error and the
    # tracing on/off overhead, printed as one `serve trace: {...}` line
    for line in serve_check.stdout.splitlines():
        if line.startswith("serve trace: "):
            try:
                parsed = json.loads(line[len("serve trace: "):])
            except ValueError:
                continue
            serve_tier["trace_tile_error"] = parsed.get("tile_error_frac")
            serve_tier["trace_overhead"] = parsed.get("overhead_frac")
    # The selfcheck's fleet phase (PR 16): the 2-shard in-process ring —
    # shard count and routed-vs-direct throughput ratio, printed as one
    # `serve fleet: {...}` line (the phase itself ASSERTS ownership
    # exactness, the kill/readmit re-warm bound and zero recompiles)
    for line in serve_check.stdout.splitlines():
        if line.startswith("serve fleet: {"):
            try:
                parsed = json.loads(line[len("serve fleet: "):])
            except ValueError:
                continue
            serve_tier["fleet_shards"] = parsed.get("shards")
            serve_tier["fleet_speedup"] = parsed.get("fleet_speedup")
    # The selfcheck's causal-plane phase (r19): the cross-process span
    # join's tiling error + critical-path histogram (`serve fleet
    # trace: {...}`), and the planted-burn incident replay — reason +
    # one-line causal story — printed as `incident: {...}`
    for line in serve_check.stdout.splitlines():
        if line.startswith("serve fleet trace: {"):
            try:
                parsed = json.loads(line[len("serve fleet trace: "):])
            except ValueError:
                continue
            serve_tier["join_tile_error"] = parsed.get("tile_error_frac")
            serve_tier["join_critical_path"] = parsed.get("critical_path")
        elif line.startswith("incident: {"):
            try:
                parsed = json.loads(line[len("incident: "):])
            except ValueError:
                continue
            serve_tier["incident_reason"] = parsed.get("reason")
            serve_tier["incident_story"] = parsed.get("story")
    for label, proc in (("selfcheck", serve_check), ("loadgen", serve_load)):
        if proc.returncode != 0:
            serve_tier[f"{label}_tail"] = (proc.stdout
                                           + proc.stderr).splitlines()[-12:]
    smoke_line = None
    for line in serve_load.stdout.splitlines():
        try:
            parsed = json.loads(line)
        except ValueError:
            continue
        if isinstance(parsed, dict) and parsed.get("kind") == "serve":
            smoke_line = parsed
    if smoke_line is not None:
        serve_tier["speedup"] = smoke_line.get(
            "speedup_batched_vs_sequential")
        compiles = smoke_line.get("compiles")
        if isinstance(compiles, dict):
            # The r10 heterogeneous-(n, d) workload: distinct compiled
            # programs under the two-axis bucket ladder vs the retired
            # per-(n, d) policy, and the warm-phase compile count (the
            # selfcheck separately ASSERTS the zero-recompile budget)
            serve_tier["hetero_cells"] = compiles.get("distinct_cells")
            serve_tier["hetero_reduction"] = compiles.get(
                "reduction_vs_per_nd")
            serve_tier["hetero_warm_compiles"] = compiles.get(
                "warm_compiles")
    telemetry.event("serve_tier", **{k: v for k, v in serve_tier.items()
                                     if not k.endswith("_tail")})
    print(f"  {serve_tier}", flush=True)

    # Tournament tier (PR 11): the attack-vs-defense smoke grid — 2
    # attacks x 2 GARs x quarantine {on, off} + the Sybil admission pair,
    # with the zero-recompile assertion armed (quarantine mask updates
    # must re-use the compiled step). Own green bit + telemetry span
    # recording the cells run.
    print("tournament tier ...", flush=True)
    with telemetry.span("tier_tournament"):
        tour_proc = subprocess.run(
            [sys.executable, "scripts/tournament.py", "--smoke"],
            cwd=ROOT, capture_output=True, text=True,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
    tournament_tier = {"returncode": tour_proc.returncode}
    for line in tour_proc.stdout.splitlines():
        if line.startswith("tournament: "):
            try:
                payload = json.loads(line[len("tournament: "):])
            except ValueError:
                continue
            tournament_tier["cells"] = payload.get("cells")
            tournament_tier["serve_cells"] = payload.get("serve_cells")
            summary = payload.get("summary") or {}
            tournament_tier["dominated"] = summary.get(
                "gars_dominated")
            tournament_tier["honest_evictions"] = summary.get(
                "honest_evictions_total")
    if tour_proc.returncode != 0:
        tournament_tier["tail"] = (tour_proc.stdout
                                   + tour_proc.stderr).splitlines()[-12:]
    telemetry.event("tournament_tier",
                    **{k: v for k, v in tournament_tier.items()
                       if k != "tail"})
    print(f"  {tournament_tier}", flush=True)

    # Cluster tier (PR 12): the multi-host recovery proof — a 2-host
    # multi-process CPU fleet trains uninterrupted, a second fleet has
    # one host SIGKILLed mid-step by the system-level FaultPlan and must
    # recover (manifest-agreed restart step, off-slice mirror,
    # auto-resume) to a BIT-IDENTICAL study CSV — plus the cross-host
    # lattice census and the zero-recompile assertion on the
    # multi-process step. Own green bit + telemetry span recording host
    # count and recovery steps. An unavailable distributed runtime is a
    # clean `unavailable` artifact with rc 0, never an rc=124 hang.
    print("cluster tier ...", flush=True)
    with telemetry.span("tier_cluster"):
        cluster_proc = subprocess.run(
            [sys.executable, "scripts/cluster_smoke.py", "--smoke",
             "--shrink-round", "--shrink-hosts", "3",
             "--straggler-round", "--straggler-hosts", "2"],
            cwd=ROOT, capture_output=True, text=True,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
    cluster_tier = {"returncode": cluster_proc.returncode}
    for line in cluster_proc.stdout.splitlines():
        if line.startswith("cluster-smoke: "):
            try:
                payload = json.loads(line[len("cluster-smoke: "):])
            except ValueError:
                continue
            cluster_tier["status"] = payload.get("status")
            cluster_tier["hosts"] = payload.get("hosts")
            cluster_tier["steps_per_sec"] = payload.get("steps_per_sec")
            cluster_tier["recovery_steps"] = payload.get("recovery_steps")
            cluster_tier["bit_identical"] = payload.get("bit_identical")
            cluster_tier["shrink_recovery_steps"] = payload.get(
                "shrink_recovery_steps")
            cluster_tier["straggler_kills"] = payload.get(
                "straggler_kills")
    if cluster_proc.returncode != 0:
        cluster_tier["tail"] = (cluster_proc.stdout
                                + cluster_proc.stderr).splitlines()[-12:]
    telemetry.event("cluster_tier",
                    **{k: v for k, v in cluster_tier.items()
                       if k != "tail"})
    print(f"  {cluster_tier}", flush=True)

    shards = {}
    for path in sorted((ROOT / "tests").glob("test_*.py")):
        print(f"slow tier: {path.name} ...", flush=True)
        with telemetry.span("tier_slow", shard=path.name):
            res = run_pytest([f"tests/{path.name}", "--runslow", "-m", "slow"])
        if res["returncode"] == 5:  # file has no slow tests
            continue
        shards[path.name] = res
        telemetry.event("tier_result", tier="slow", shard=path.name,
                        **{k: v for k, v in res.items() if k != "tail"})
        telemetry.counter("tests_passed", res["passed"])
        telemetry.counter("tests_failed", res["failed"])
        print(f"  {res}", flush=True)

    slow_total = {
        "passed": sum(s["passed"] for s in shards.values()),
        "failed": sum(s["failed"] for s in shards.values()),
        "skipped": sum(s["skipped"] for s in shards.values()),
        "seconds": round(sum(s["seconds"] for s in shards.values()), 1),
    }
    out = {
        "round": args.round,
        "git_head": head,
        "host": "1-core TPU build host (slow tier sharded by file "
                "because one --runslow run exceeds a review window)",
        "obs_selfcheck": obs_selfcheck,
        "bench_compare": bench_compare,
        "lint_tier": lint_tier,
        "locks_tier": locks_tier,
        "lattice_tier": lattice_tier,
        "default_tier": default,
        "nopallas_tier": nopallas,
        "serve_tier": serve_tier,
        "tournament_tier": tournament_tier,
        "cluster_tier": cluster_tier,
        "slow_tier_total": slow_total,
        "slow_tier_shards": shards,
        "telemetry": telemetry.path.name,
        "green": bool(default["failed"] == 0 and default["errors"] == 0
                      and default["returncode"] == 0
                      and obs_selfcheck["returncode"] == 0
                      and bench_compare["returncode"] == 0
                      and lint_tier["returncode"] == 0
                      and locks_tier["returncode"] == 0
                      and lattice_tier["returncode"] == 0
                      and nopallas["failed"] == 0
                      and nopallas["returncode"] == 0
                      and serve_tier["returncode"] == 0
                      and tournament_tier["returncode"] == 0
                      and cluster_tier["returncode"] == 0
                      and slow_total["failed"] == 0
                      and all(s["returncode"] == 0 for s in shards.values())),
    }
    telemetry.event("run_end", green=out["green"],
                    passed=default["passed"] + slow_total["passed"],
                    failed=default["failed"] + slow_total["failed"],
                    seconds=default["seconds"] + slow_total["seconds"])
    telemetry.close()
    path = pathlib.Path(args.out) if args.out else (
        ROOT / f"TESTS_r{args.round:02d}.json")
    path.write_text(json.dumps(out, indent=2) + "\n")
    print(json.dumps({k: out[k] for k in
                      ("round", "git_head", "green")}
                     | {"default": default["passed"],
                        "slow": slow_total["passed"]}))


if __name__ == "__main__":
    main()
