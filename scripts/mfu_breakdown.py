#!/usr/bin/env python3
"""Attribute the headline benchmark's step time to its phases (VERDICT r3
weak #1: "no published breakdown shows what bounds the remaining MFU").

Times, on the real TPU, separately-compiled slices of the n=25 f=5 CIFAR-10
bulyan cell at the benchmark's own settings (bf16-mixed, M=20 steps per
dispatch, device-resident data):

  full        — the exact benchmark program (honest + attack + bulyan +
                update + 24-column study metrics)
  no_study    — same minus the study-metric computation
  cheap_agg   — honest + update only (average GAR, no attack): the floor of
                the honest phase + momentum/update algebra
  honest_only — just `_phase_honest` (vmapped/grouped fwd+bwd + clip +
                momentum rows), M dispatches pipelined
  bulyan_only — the bulyan kernel alone on a live (25, d) matrix
  empire_only — the empire attack synthesis alone (incl. its defense call)

and derives per-step milliseconds for each attributed term. Writes
MFU_BREAKDOWN.json at the repo root and prints one JSON line.

Caveat: the `*_only` solo cells carry the per-dispatch host round-trip
(~2.5 ms/program idle, much more when the host is busy) spread over their
M=20 in-program iterations; their in-program cost is far smaller. The
trustworthy attribution is the DELTAS between the full-engine rows
(`full`, `no_study`, `cheap_agg`, `honest_only`), whose device time
dominates the dispatch floor.

Usage: python scripts/mfu_breakdown.py [--min-measure-s 4]
"""

import argparse
import json
import os
import pathlib
import sys
import time

os.environ.setdefault("BMT_SYNTH_TRAIN", "5000")
os.environ.setdefault("BMT_SYNTH_TEST", "500")

import numpy as np

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from byzantinemomentum_tpu import attacks, data, losses, models, ops  # noqa: E402
from byzantinemomentum_tpu.data.device import DeviceData  # noqa: E402
from byzantinemomentum_tpu.engine import EngineConfig, build_engine  # noqa: E402

N, F, BATCH, M = 25, 5, 50, 20


def build(nb_for_study, gar_name="bulyan", attack_name="empire"):
    cfg = EngineConfig(
        nb_workers=N, nb_decl_byz=F, nb_real_byz=F,
        nb_for_study=nb_for_study, nb_for_study_past=1,
        momentum=0.99, momentum_at="update", gradient_clip=5.0,
        compute_dtype="bfloat16")
    engine = build_engine(
        cfg=cfg, model_def=models.build("empire-cnn"),
        loss=losses.Loss("nll"), criterion=losses.Criterion("top-k"),
        defenses=[(ops.gars[gar_name], 1.0, {})],
        attack=attacks.attacks[attack_name], attack_kwargs={"factor": 1.1})
    return cfg, engine


def timed(dispatch, sync, *, min_s, warmup=2):
    """steps/s of `dispatch()` (returns a sync handle consumed by `sync`),
    depth-2 pipelined like bench.py."""
    for _ in range(warmup):
        h = dispatch()
    if warmup:
        sync(h)
    steps = 0
    pending = []
    start = time.monotonic()
    while True:
        pending.append(dispatch())
        steps += M
        if steps >= 400:
            break
        if len(pending) >= 2:
            sync(pending.pop(0))
            if time.monotonic() - start >= min_s:
                break
    for p in pending:
        sync(p)
    elapsed = time.monotonic() - start
    return steps / elapsed


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--min-measure-s", type=float, default=4.0)
    args = parser.parse_args()
    min_s = args.min_measure_s

    trainset, _ = data.make_datasets("cifar10", BATCH, BATCH, seed=0)
    train_data = DeviceData(trainset)
    lrs = jnp.full((M,), 0.01, jnp.float32)
    rates = {}

    # --- full benchmark program and ablations --- #
    for name, nb_study, gar, atk in (
            ("full", 1, "bulyan", "empire"),
            ("no_study", 0, "bulyan", "empire"),
            ("cheap_agg", 0, "average", "empire")):
        cfg, engine = build(nb_study, gar, atk)
        engine.attach_data(train_data)
        state = engine.init(jax.random.PRNGKey(0))
        S = cfg.nb_sampled

        def dispatch():
            idx, flips = train_data.sample_indices(S * M)
            nonlocal state
            state, metrics = engine.train_multi_indexed(
                state,
                jnp.asarray(idx.reshape((M, S) + idx.shape[1:])),
                jnp.asarray(flips.reshape((M, S) + flips.shape[1:])), lrs)
            return metrics.get("Defense gradient norm", state.steps + 0)

        rates[name] = timed(dispatch, lambda h: np.asarray(h), min_s=min_s)

    # --- honest phase only (M pipelined dispatches of _phase_honest) --- #
    cfg, engine = build(0)
    engine.attach_data(train_data)
    state = engine.init(jax.random.PRNGKey(0))
    S = cfg.nb_sampled

    def honest_multi(state, idx, flips, lr):
        def body(st, inp):
            i, fl = inp
            xs, ys = train_data.gather(i, fl)
            out = engine._phase_honest(st, xs, ys, lr)
            # Thread rng through so the M iterations are sequential like the
            # real program; consume the WHOLE honest matrix (a row-0-only
            # payload would let XLA dead-code-eliminate the other rows'
            # clip scaling)
            st = st._replace(rng=out[0])
            return st, jnp.sum(out[6])
        return jax.lax.scan(body, state, (idx, flips))

    honest_jit = jax.jit(honest_multi)

    def dispatch_honest():
        idx, flips = train_data.sample_indices(S * M)
        nonlocal_state = dispatch_honest.state
        st, payload = honest_jit(
            nonlocal_state,
            jnp.asarray(idx.reshape((M, S) + idx.shape[1:])),
            jnp.asarray(flips.reshape((M, S) + flips.shape[1:])),
            jnp.float32(0.01))
        dispatch_honest.state = st
        return payload

    dispatch_honest.state = state
    rates["honest_only"] = timed(dispatch_honest, lambda h: np.asarray(h),
                                 min_s=min_s)

    # --- bulyan kernel alone on a live (N, d) matrix --- #
    d = engine.d
    G = jax.random.normal(jax.random.PRNGKey(1), (N, d), jnp.float32)

    @jax.jit
    def bulyan_multi(G):
        def body(carry, _):
            out = ops.gars["bulyan"].unchecked(G + carry, f=F)
            return jnp.sum(out) * 1e-20, out[0]
        return jax.lax.scan(body, jnp.float32(0.0), None, length=M)

    rates["bulyan_only"] = timed(lambda: bulyan_multi(G)[1],
                                 lambda h: np.asarray(h), min_s=min_s)

    # --- empire attack synthesis alone (with its one defense call) --- #
    Gh = jax.random.normal(jax.random.PRNGKey(2), (N - F, d), jnp.float32)
    defense = lambda gradients, f: ops.gars["bulyan"].unchecked(gradients, f=f)

    @jax.jit
    def empire_multi(Gh):
        def body(carry, _):
            byz = attacks.attacks["empire"].unchecked(
                Gh + carry, f_decl=F, f_real=F, defense=defense, factor=1.1)
            return jnp.sum(byz) * 1e-20, byz[0, 0]
        return jax.lax.scan(body, jnp.float32(0.0), None, length=M)

    rates["empire_only"] = timed(lambda: empire_multi(Gh)[1],
                                 lambda h: np.asarray(h), min_s=min_s)

    ms = {k: 1000.0 / v for k, v in rates.items()}
    breakdown = {
        "study_metrics_ms": ms["full"] - ms["no_study"],
        "attack_plus_gar_ms": ms["no_study"] - ms["cheap_agg"],
        "honest_phase_ms": ms["honest_only"],
        "update_and_rest_ms": ms["cheap_agg"] - ms["honest_only"],
        "bulyan_kernel_solo_ms": ms["bulyan_only"],
        "empire_attack_solo_ms": ms["empire_only"],
        "full_step_ms": ms["full"],
    }
    out = {
        "config": f"CIFAR-10 empire-cnn n={N} f={F} batch {BATCH} "
                  f"bulyan vs empire(1.1), bf16-mixed, M={M} steps/dispatch, "
                  "device-resident data (the BENCH_r* headline cell)",
        "steps_per_sec": rates,
        "per_step_ms": ms,
        "attribution_ms": breakdown,
        "device_kind": jax.devices()[0].device_kind,
    }
    path = pathlib.Path(__file__).resolve().parent.parent / "MFU_BREAKDOWN.json"
    path.write_text(json.dumps(out, indent=2) + "\n")
    print(json.dumps(out))


if __name__ == "__main__":
    main()
