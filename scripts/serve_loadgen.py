#!/usr/bin/env python3
"""Open-loop Poisson load generator for the aggregation service.

Measures the serving engine the production way and writes a
machine-readable `BENCH_serve.json` (`"kind": "serve"`) that
`scripts/bench_compare.py` gates and `scripts/bench_history.py` renders:

  serve.sequential  closed-loop single-request dispatch (max_batch=1,
                    submit -> result -> repeat): the baseline every
                    batching claim is measured against.
  serve.batched     saturation throughput: every request submitted
                    up front (an open loop at infinite rate), the
                    microbatcher packing full batches — aggregations/s
                    at capacity, plus the realized batch occupancy.
  serve.open_loop   Poisson arrivals at `--rate` (default: 60% of the
                    measured batched capacity): the latency numbers —
                    p50/p99 of submit->resolve per request. Open loop
                    means arrivals do NOT wait for completions, so
                    queueing delay is measured honestly rather than
                    hidden by a closed loop's self-throttling
                    (the coordinated-omission trap).
  serve.hetero      heterogeneous-(n, d) workload (r10): one rule per
                    kernel family, each spanning >= 3 raw row counts and
                    >= 3 raw widths. Measures the COLD phase (every
                    distinct raw shape once, sequentially — the
                    cold-start tail each novel shape pays) and the WARM
                    phase (mixed saturation traffic) with XLA compile
                    counts for both, plus the `compiles` policy
                    comparison: distinct compiled cells under the
                    two-axis bucket ladder vs what the per-(n, d) PR 8
                    policy (exact n for non-masked rules, exact d for
                    every rule) would have compiled for the same stream.

Since r18 the default payload also carries the single-process scenario
battery — `serve.rotation` / `serve.zipf` / `serve.churn` /
`serve.flash`: the `--fleet` population scenarios (same
`_scenario_bases` key streams) driven straight through
`service.submit` with no router or socket in the path, so the
engine's behaviour under skew, churn and flash arrival is gated by
`compare_serve` independently of the fleet plumbing.

The p99 contract is also checked: a correctly-batched service bounds
p99 by `max_delay` (the longest a request waits for batch-mates) plus
one program execution (measured warm) — the artifact records the bound
and whether the run met it.

Trace-collection mode (`--trace`, r13): the same open-loop probe run
with request tracing (`obs/trace`) on, writing `ATTRIB_serve.json`
(`"kind": "serve_attribution"`) — the serving twin of the training
`attribution.json`: per-phase p50/p99/mean ms (validate, queue wait,
pack, dispatch, resolver wake-up, device, resolve) whose means TILE the
measured request latency (the artifact records the tiling error and the
15% acceptance bit), the queue-depth and batch-occupancy distributions
each request observed, and the tracing-on-vs-off throughput overhead
(paired saturation windows, median of per-pair ratios — robust to the
1-core host's drift). `bench_compare.py` gates two of these per phase;
committed rounds live as `ATTRIB_serve_r*.json`. Since r16 the payload
also carries the `router` block: the 2-shard fleet router's `route` +
`shard_rtt` spans and their tiling against the client-measured wall.

Metrics-overhead mode (`--metrics-overhead`, r18): the metrics-plane
acceptance measurement — paired saturation windows against TWO
services (registry live vs `NullRegistry`; the registry is bound at
construction, so unlike tracing it cannot be toggled on one service),
median of per-pair throughput ratios, written as `BENCH_metrics.json`
(`"kind": "metrics_overhead"`) with the 2% `bound_frac` acceptance
bit, gated by `bench_compare.py compare_metrics`.

Fleet mode (`--fleet`, r16): scenario traffic (`FLEET_SCENARIOS`)
through a real consistent-hash `FleetRouter` TCP front door at each
`--shards` count, plus the kill-safe failover round (shard killed
mid-traffic: parked line recovers, survivor verdicts untouched,
returning arc re-warms no faster than a fresh id). Writes
`BENCH_serve_fleet.json` (`"kind": "serve_fleet"`), gated by
`bench_compare.py compare_serve_fleet`. Shards are in-process
(`serve/fleet/local.py`) — see `run_fleet` for why; `--router
HOST:PORT` drives an external `python -m byzantinemomentum_tpu
.serve.fleet` instead.

Usage:
  python scripts/serve_loadgen.py [--smoke] [--out BENCH_serve.json]
  python scripts/serve_loadgen.py --requests 600 --rate 400
  python scripts/serve_loadgen.py --trace [--out ATTRIB_serve.json]
  python scripts/serve_loadgen.py --fleet --shards 1,2,4
  python scripts/serve_loadgen.py --metrics-overhead

All traffic runs against the in-process `AggregationService` (the same
engine the socket front end wraps) on one cell, client ids attached, so
the measured path includes packing, suspicion scoring and verdicts.
"""

import argparse
import json
import pathlib
import sys
import time

import numpy as np

ROOT = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(ROOT))

__all__ = ["run_loadgen", "run_hetero", "run_trace", "run_fleet",
           "run_fleet_trace", "run_router_trace", "run_metrics_overhead",
           "pr8_policy_cells", "percentiles", "FLEET_SCENARIOS", "main"]

# Named fleet population scenarios (`--fleet`): how client ids arrive.
#   rotation  uniform round-robin over a fixed population — the
#             best-case spread the consistent-hash ring should match;
#   zipf      heavy-tailed popularity (a few hot clients dominate) —
#             the worst realistic case for per-shard balance;
#   churn     generational turnover (ids appear, age out, never
#             return) — exercises store growth + eviction, and the ring
#             mapping fresh ids across all arcs;
#   flash     flash crowd — burst ARRIVAL, not key skew: a trickle of
#             requests at low concurrency, then the whole remaining
#             crowd at once at 4x the configured connection count.
#             Exercises admission under a connection storm (accept
#             queue, per-shard pipelining, microbatcher fill) where the
#             other scenarios only vary WHICH keys arrive.
FLEET_SCENARIOS = ("rotation", "zipf", "churn", "flash")


def percentiles(latencies_ms):
    """{p50_ms, p90_ms, p99_ms, mean_ms} of a latency sample."""
    arr = np.asarray(latencies_ms, dtype=np.float64)
    return {
        "p50_ms": round(float(np.percentile(arr, 50)), 3),
        "p90_ms": round(float(np.percentile(arr, 90)), 3),
        "p99_ms": round(float(np.percentile(arr, 99)), 3),
        "mean_ms": round(float(arr.mean()), 3),
    }


def _cohorts(rng, requests, n, d):
    """Pre-generated request payloads (generation must not pollute the
    measured window)."""
    return [rng.standard_normal((n, d)).astype(np.float32)
            for _ in range(requests)]


def _submit(service, cohort, gar, f, clients):
    return service.submit(cohort, gar=gar, f=f, client_ids=clients)


def _sequential(service, cohorts, gar, f, clients):
    """Closed-loop single-request dispatch: the baseline."""
    latencies = []
    t0 = time.perf_counter()
    for cohort in cohorts:
        result = _submit(service, cohort, gar, f, clients).result(timeout=60)
        latencies.append(result.latency_ms)
    wall = time.perf_counter() - t0
    return {"agg_per_sec": round(len(cohorts) / wall, 2),
            "wall_s": round(wall, 3), **percentiles(latencies)}


def _saturation(service, cohorts, gar, f, clients):
    """Submit everything up front; the batcher packs at capacity."""
    t0 = time.perf_counter()
    futures = [_submit(service, cohort, gar, f, clients)
               for cohort in cohorts]
    latencies = [fut.result(timeout=120).latency_ms for fut in futures]
    wall = time.perf_counter() - t0
    stats = service.stats()
    batches = stats["cache"]["hits"] + stats["cache"]["misses"]
    return {"agg_per_sec": round(len(cohorts) / wall, 2),
            "wall_s": round(wall, 3),
            "mean_batch": round(len(cohorts) / max(batches, 1), 2),
            **percentiles(latencies)}


def _open_loop(service, cohorts, gar, f, clients, rate, rng):
    """Poisson arrivals at `rate`/s; arrivals never wait for completions."""
    gaps = rng.exponential(1.0 / rate, size=len(cohorts))
    arrivals = np.cumsum(gaps)
    futures = []
    t0 = time.perf_counter()
    for cohort, due in zip(cohorts, arrivals):
        delay = t0 + due - time.perf_counter()
        if delay > 0:
            time.sleep(delay)
        futures.append(_submit(service, cohort, gar, f, clients))
    latencies = [fut.result(timeout=120).latency_ms for fut in futures]
    wall = time.perf_counter() - t0
    return {"rate_per_sec": round(rate, 2),
            "agg_per_sec": round(len(cohorts) / wall, 2),
            **percentiles(latencies)}


def run_loadgen(*, requests=400, n=11, d=128, f=2, gar="krum",
                max_batch=8, max_delay_ms=5.0, rate=None, seed=1,
                repeats=2, heterogeneous=True, hetero_repeats=8,
                population=64):
    """The measurement phases; returns the artifact payload (no file I/O
    here — tests call this directly). Throughput phases run `repeats`
    windows and keep the fastest — the standard damping for scheduler
    noise on shared/1-core CI hosts. `heterogeneous` adds the mixed
    -(n, d) workload phase (`run_hetero`) and its `compiles` policy
    comparison to the artifact. `population` sizes the key space of the
    single-process scenario cells (`serve.rotation` etc.)."""
    import jax

    from byzantinemomentum_tpu.serve import AggregationService

    # Cap GIL holds at 1 ms for the measurement process: the default 5 ms
    # switch interval lets one numpy-packing slice stall the submitter
    # for longer than the whole latency budget, which would charge pure
    # scheduler jitter to the service's p99 (the serve CLI sets the same
    # knob for real serving processes)
    old_switch = sys.getswitchinterval()
    sys.setswitchinterval(0.001)
    try:
        payload = _run_loadgen(requests, n, d, f, gar, max_batch,
                               max_delay_ms, rate, seed, repeats,
                               AggregationService, jax.default_backend(),
                               population=population)
        if heterogeneous:
            hetero = run_hetero(repeats_per_shape=hetero_repeats,
                                max_batch=max_batch,
                                max_delay_ms=max_delay_ms, seed=seed)
            payload["cells"]["serve.hetero"] = hetero["hetero_cell"]
            payload["cold_start"] = hetero["cold"]
            payload["compiles"] = hetero["compiles"]
        return payload
    finally:
        sys.setswitchinterval(old_switch)


def _best(runs, key="agg_per_sec"):
    return max(runs, key=lambda r: r[key])


def pr8_policy_cells(shapes):
    """Distinct compiled CELLS the retired per-(n, d) PR 8 policy would
    need for a request stream of `(gar, f, n, d)` shapes: only
    average/median/trmean/krum rode padded row buckets, everything else
    compiled per exact n, and EVERY rule compiled per exact d. The
    counterfactual the r10 two-axis ladder is measured against."""
    from byzantinemomentum_tpu.serve.programs import N_BUCKETS

    legacy_masked = {"average", "median", "trmean", "krum"}
    cells = set()
    for gar, f, n, d in shapes:
        if gar in legacy_masked:
            nb = next(b for b in N_BUCKETS if n <= b)
        else:
            nb = n
        cells.add((gar, nb, f, d))
    return len(cells)


def run_hetero(*, repeats_per_shape=8, max_batch=8, max_delay_ms=5.0,
               seed=1):
    """The heterogeneous-(n, d) phase: cold-start tail, warm mixed
    traffic, and the compile-count policy comparison. Returns the
    artifact fragment (`hetero` cell + `compiles` summary)."""
    from byzantinemomentum_tpu.analysis import contracts
    from byzantinemomentum_tpu.serve import AggregationService
    from byzantinemomentum_tpu.serve.__main__ import HETERO_FAMILIES

    rng = np.random.default_rng(seed)
    shapes = [(gar, f, n, d) for gar, f, ns, ds in HETERO_FAMILIES
              for n in ns for d in ds]
    with AggregationService(max_batch=max_batch,
                            max_delay_ms=max_delay_ms) as svc:
        # COLD: every distinct raw shape once, sequentially, against an
        # entirely unwarmed cache — each novel CELL pays its compile
        # inside the measured latency, which is exactly the tail the
        # bucket ladder exists to amortize (novel shapes that share a
        # cell land warm even here)
        cold_lat = []
        with contracts.count_compiles() as cold_log:
            for gar, f, n, d in shapes:
                cohort = rng.standard_normal((n, d)).astype(np.float32)
                result = svc.aggregate(cohort, gar=gar, f=f,
                                       diagnostics=False, timeout=120)
                cold_lat.append(result.latency_ms)
        # Finish warming: the cold pass ran sequential batches of 1, so
        # the larger batch buckets (which saturation traffic will pack)
        # still owe their compiles — pre-execute them the way a real
        # deployment's warmup would
        svc.warmup([(gar, n, f, d, False) for gar, f, n, d in shapes])
        # WARM: saturation traffic round-robining every shape — mixed raw
        # shapes of one cell microbatch together; zero compiles expected
        warm_payloads = [
            (gar, f, rng.standard_normal((n, d)).astype(np.float32))
            for _ in range(repeats_per_shape)
            for gar, f, n, d in shapes]
        t0 = time.perf_counter()
        with contracts.count_compiles() as warm_log:
            futures = [svc.submit(m, gar=gar, f=f, diagnostics=False)
                       for gar, f, m in warm_payloads]
            warm_lat = [fut.result(timeout=120).latency_ms
                        for fut in futures]
        wall = time.perf_counter() - t0
        stats = svc.stats()

    distinct_cells = stats["cache"]["cells"]
    pr8_cells = pr8_policy_cells(shapes)
    return {
        "hetero_cell": {
            "agg_per_sec": round(len(warm_payloads) / wall, 2),
            **percentiles(warm_lat),
        },
        "cold": {"shapes": len(shapes),
                 "compiles": cold_log.count,
                 **percentiles(cold_lat),
                 "max_ms": round(float(np.max(cold_lat)), 3)},
        "compiles": {
            "families": len(HETERO_FAMILIES),
            "shapes": len(shapes),
            "warm_requests": len(warm_payloads),
            "warm_compiles": warm_log.count,
            "distinct_cells": distinct_cells,
            "distinct_programs": stats["cache"]["programs"],
            "per_nd_policy_cells": pr8_cells,
            "reduction_vs_per_nd": round(
                pr8_cells / max(distinct_cells, 1), 2),
        },
    }


def run_trace(*, requests=400, n=11, d=128, f=2, gar="krum", max_batch=8,
              max_delay_ms=5.0, rate=None, seed=1, overhead_pairs=8,
              tile_tolerance=0.15):
    """Trace-collection mode: the `ATTRIB_serve.json` payload.

    Phases: (1) tracing OVERHEAD — `overhead_pairs` interleaved
    on/off/off/on saturation windows; the median of per-pair throughput
    ratios estimates the cost (pairing cancels host drift, the median
    ignores outlier windows); (2) the open-loop Poisson probe at half the
    measured capacity with tracing on, every response's trace collected:
    per-phase p50/p99/mean ms, the tiling check (phase means must sum to
    the mean measured latency within `tile_tolerance`), and the
    queue-depth / batch-occupancy distributions the traces carried."""
    import statistics

    import jax

    from byzantinemomentum_tpu.serve import AggregationService

    old_switch = sys.getswitchinterval()
    sys.setswitchinterval(0.001)
    rng = np.random.default_rng(seed)
    clients = tuple(f"client-{i}" for i in range(n))
    try:
        with AggregationService(max_batch=max_batch,
                                max_delay_ms=max_delay_ms) as service:
            service.warmup([(gar, n, f, d, True)])

            def window(count=max(100, requests // 4)):
                t0 = time.perf_counter()
                futures = [_submit(service, c, gar, f, clients)
                           for c in _cohorts(rng, count, n, d)]
                for fut in futures:
                    fut.result(timeout=120)
                return count / (time.perf_counter() - t0)

            window(50)  # warm the measurement path itself
            ratios, on_rates, off_rates = [], [], []
            for _ in range(overhead_pairs):
                service.tracing = True
                a_on = window()
                service.tracing = False
                a_off = window()
                b_off = window()
                service.tracing = True
                b_on = window()
                ratios.append((a_on + b_on) / (a_off + b_off))
                on_rates += [a_on, b_on]
                off_rates += [a_off, b_off]
            overhead = max(0.0, 1.0 - statistics.median(ratios))

            # Open-loop probe, tracing on: the trace stream that becomes
            # the per-phase attribution
            service.tracing = True
            if rate is None:
                rate = max(1.0, 0.5 * max(on_rates))
            cohorts = _cohorts(rng, requests, n, d)
            gaps = rng.exponential(1.0 / rate, size=len(cohorts))
            arrivals = np.cumsum(gaps)
            futures = []
            t0 = time.perf_counter()
            for cohort, due in zip(cohorts, arrivals):
                delay = t0 + due - time.perf_counter()
                if delay > 0:
                    time.sleep(delay)
                futures.append(_submit(service, cohort, gar, f, clients))
            results = [fut.result(timeout=120) for fut in futures]

        phases = {}
        depths, occupancies = [], []
        latencies = []
        for result in results:
            for phase, ms in result.trace.spans_ms().items():
                phases.setdefault(phase, []).append(ms)
            record = result.trace
            if record.depth_at_submit is not None:
                depths.append(float(record.depth_at_submit))
            if record.batch_occupancy is not None:
                occupancies.append(float(record.batch_occupancy))
            latencies.append(result.latency_ms)

        from byzantinemomentum_tpu.obs.trace.request import LATENCY_PHASES
        span_sum_mean = sum(
            sum(phases[p]) / len(phases[p])
            for p in LATENCY_PHASES if phases.get(p))
        latency_mean = float(np.mean(latencies))
        tile_error = abs(span_sum_mean - latency_mean) \
            / max(latency_mean, 1e-9)

        def dist(values):
            return {**percentiles(values),
                    "max_ms": round(float(np.max(values)), 3)}

        return {
            "kind": "serve_attribution",
            "backend": jax.default_backend(),
            "config": {"requests": requests, "n": n, "d": d, "f": f,
                       "gar": gar, "max_batch": max_batch,
                       "max_delay_ms": max_delay_ms, "seed": seed,
                       "rate_per_sec": round(float(rate), 2)},
            "phases": {phase: dist(values)
                       for phase, values in sorted(phases.items())},
            "latency": dist(latencies),
            "tile": {
                "span_sum_mean_ms": round(span_sum_mean, 4),
                "latency_mean_ms": round(latency_mean, 4),
                "error_frac": round(tile_error, 4),
                "within_tolerance": bool(tile_error <= tile_tolerance),
                "tolerance": tile_tolerance,
            },
            "queue_depth": ({k.replace("_ms", ""): v
                             for k, v in dist(depths).items()}
                            if depths else None),
            "batch_occupancy": ({k.replace("_ms", ""): v
                                 for k, v in dist(occupancies).items()}
                                if occupancies else None),
            "overhead": {
                "pairs": overhead_pairs,
                "agg_per_sec_tracing_on": round(max(on_rates), 2),
                "agg_per_sec_tracing_off": round(max(off_rates), 2),
                "ratio_median": round(statistics.median(ratios), 4),
                "frac": round(overhead, 4),
            },
        }
    finally:
        sys.setswitchinterval(old_switch)


def run_metrics_overhead(*, requests=400, n=11, d=128, f=2, gar="krum",
                         max_batch=8, max_delay_ms=5.0, seed=1,
                         overhead_pairs=8, bound_frac=0.02):
    """Metrics-plane overhead mode: the `BENCH_metrics.json` payload.

    Unlike tracing (a runtime toggle), the registry is a CONSTRUCTOR
    -time choice — hot-path handles are bound in `__init__` — so the
    on/off arms are TWO services, one with a live `MetricsRegistry` and
    one with the `NullRegistry`, both warmed, measured in interleaved
    a_on/a_off/b_off/b_on saturation windows per pair (pairing cancels
    host drift; the median of per-pair throughput ratios ignores
    outlier windows — the same estimator `run_trace` uses for tracing
    overhead). Both arms run with request TRACING disabled: with
    tracing on, every completed trace feeds the per-phase
    `serve_phase_*_ms` histograms (span math + 7 observes per request)
    — a cost of the TRACING plane, measured and gated by the
    ATTRIB_serve overhead number, not of the registry this bound
    governs. What's measured here is the registry proper: the
    per-request counter bumps and the latency/occupancy histogram
    observes on the serving hot path. Acceptance: `overhead_frac <=
    bound_frac` (the r18 2% ceiling on agg/s). The payload carries a
    sample of the live arm's registry dump so the artifact proves the
    measured service was actually metering, not silently running the
    null registry."""
    import statistics

    import jax

    from byzantinemomentum_tpu.serve import AggregationService

    old_switch = sys.getswitchinterval()
    sys.setswitchinterval(0.001)
    rng = np.random.default_rng(seed)
    clients = tuple(f"client-{i}" for i in range(n))
    count = max(100, requests // 4)
    try:
        with AggregationService(max_batch=max_batch,
                                max_delay_ms=max_delay_ms,
                                tracing=False, metrics=True) as svc_on, \
             AggregationService(max_batch=max_batch,
                                max_delay_ms=max_delay_ms,
                                tracing=False, metrics=False) as svc_off:
            svc_on.warmup([(gar, n, f, d, True)])
            svc_off.warmup([(gar, n, f, d, True)])

            def window(service):
                t0 = time.perf_counter()
                futures = [_submit(service, c, gar, f, clients)
                           for c in _cohorts(rng, count, n, d)]
                for fut in futures:
                    fut.result(timeout=120)
                return count / (time.perf_counter() - t0)

            window(svc_on)   # warm the measurement path on both arms
            window(svc_off)
            ratios, on_rates, off_rates = [], [], []
            for _ in range(overhead_pairs):
                a_on = window(svc_on)
                a_off = window(svc_off)
                b_off = window(svc_off)
                b_on = window(svc_on)
                ratios.append((a_on + b_on) / (a_off + b_off))
                on_rates += [a_on, b_on]
                off_rates += [a_off, b_off]
            overhead = max(0.0, 1.0 - statistics.median(ratios))
            dump = svc_on.metrics.dump()

        metered = dump["metrics"]
        latency = metered.get("serve_request_ms", {})
        return {
            "kind": "metrics_overhead",
            "backend": jax.default_backend(),
            "config": {"requests": requests, "n": n, "d": d, "f": f,
                       "gar": gar, "max_batch": max_batch,
                       "max_delay_ms": max_delay_ms, "seed": seed,
                       "window_requests": count},
            "pairs": overhead_pairs,
            "agg_per_sec_metrics_on": round(max(on_rates), 2),
            "agg_per_sec_metrics_off": round(max(off_rates), 2),
            "ratio_median": round(statistics.median(ratios), 4),
            "overhead_frac": round(overhead, 4),
            "bound_frac": bound_frac,
            "within_bound": bool(overhead <= bound_frac),
            "registry_sample": {
                "schema": dump["schema"],
                "source": dump.get("source"),
                "names": sorted(metered),
                "serve_requests":
                    metered.get("serve_requests", {}).get("value", 0),
                "serve_request_ms_count": latency.get("count", 0),
            },
        }
    finally:
        sys.setswitchinterval(old_switch)


def _scenario_bases(name, requests, population, rng):
    """The routing-key stream of one named scenario: request k's cohort
    is keyed by its FIRST client id, so these bases are what the ring
    actually routes on (the rest of each cohort rides along)."""
    if name == "rotation":
        return [f"r{k % population}" for k in range(requests)]
    if name == "zipf":
        ranks = np.minimum(rng.zipf(1.2, size=requests),
                           population).astype(int) - 1
        return [f"z{int(r)}" for r in ranks]
    if name == "churn":
        # A new generation of ids every 2*population requests; old
        # generations never return (eviction-shaped traffic)
        return [f"ch{(k % population) + (k // (2 * population)) * population}"
                for k in range(requests)]
    if name == "flash":
        # Deliberately uniform keys — the scenario's stress is in the
        # ARRIVAL pattern (`_drive_flash`), not the key distribution
        return [f"fl{k % population}" for k in range(requests)]
    raise ValueError(f"unknown fleet scenario {name!r} "
                     f"(have {FLEET_SCENARIOS})")


def _drive_router(host, port, payloads, connections=8):
    """Closed-loop client pool against a router (or single-server)
    socket: `connections` threads, each with its own connection, each
    one request in flight — concurrency comes from the pool, so the
    router's per-shard pipelining and the shards' microbatchers see
    parallel traffic. Returns (wall_s, latencies_ms, errors)."""
    import queue as queue_mod
    import threading

    from byzantinemomentum_tpu.serve.fleet.local import (ask_socket,
                                                         fleet_socket)

    work = queue_mod.Queue()
    for payload in payloads:
        work.put(payload)
    lock = threading.Lock()  # bmt: noqa[BMT-L06] load-generator client-side tally lock; the loadgen is test tooling, not fleet code
    latencies, errors = [], [0]

    def client():
        sock, files = fleet_socket(host, port, timeout=120)
        try:
            while True:
                try:
                    request = work.get_nowait()
                except queue_mod.Empty:
                    return
                t0 = time.perf_counter()
                try:
                    reply = ask_socket(files, request)
                except OSError:
                    reply = {"ok": False}
                ms = (time.perf_counter() - t0) * 1000.0
                with lock:
                    if reply.get("ok"):
                        latencies.append(ms)
                    else:
                        errors[0] += 1
        finally:
            sock.close()

    threads = [threading.Thread(target=client, name=f"loadgen-client-{i}",
                                daemon=True)
               for i in range(connections)]
    t0 = time.perf_counter()
    for thread in threads:
        thread.start()
    for thread in threads:
        thread.join()
    return time.perf_counter() - t0, latencies, errors[0]


def _drive_flash(host, port, payloads, connections=8):
    """The flash-crowd arrival shape: ~25% of the payloads trickle in at
    a quarter of the configured concurrency (the calm before), then the
    whole remaining crowd arrives at once at 4x concurrency — every
    burst connection dials in the same instant, so the router's accept
    path, per-shard pipelining and the shards' microbatchers absorb a
    connection storm rather than a steady pool. Returns
    (wall_s, latencies_ms, errors, burst_block); latencies/errors merge
    both phases so the row keeps the standard scenario shape, and the
    burst phase is broken out separately in `burst_block`."""
    split = max(1, len(payloads) // 4)
    trickle, crowd = payloads[:split], payloads[split:]
    wall_t, lat_t, err_t = _drive_router(
        host, port, trickle, connections=max(1, connections // 4))
    burst_connections = connections * 4
    wall_b, lat_b, err_b = _drive_router(
        host, port, crowd, connections=burst_connections)
    burst = {
        "requests": len(crowd),
        "connections": burst_connections,
        "agg_per_sec": round(len(lat_b) / max(wall_b, 1e-9), 2),
        "errors": err_b,
        **({k: v for k, v in percentiles(lat_b).items()} if lat_b
           else {}),
    }
    return wall_t + wall_b, lat_t + lat_b, err_t + err_b, burst


def _drive_scenario(name, host, port, payloads, connections):
    """Dispatch one named scenario through its arrival shape. Returns
    (wall_s, latencies_ms, errors, extra_row_fields)."""
    if name == "flash":
        wall, lat, errors, burst = _drive_flash(host, port, payloads,
                                                connections)
        return wall, lat, errors, {"burst": burst}
    wall, lat, errors = _drive_router(host, port, payloads, connections)
    return wall, lat, errors, {}


def _fleet_payloads(bases, n, d, f, gar, rng):
    return [{"op": "aggregate", "gar": gar, "f": f,
             "vectors": rng.standard_normal((n, d)).astype(
                 np.float32).tolist(),
             "clients": [base] + [f"{base}.{j}" for j in range(1, n)]}
            for base in bases]


def _fleet_recovery(fleet, *, n, d, f, gar, rng):
    """The kill-safe failover round on a live fleet: kill one shard,
    verify (a) a line routed to the dead arc PARKS (on_dead="queue")
    and completes after the restart, (b) the survivor's verdict stream
    is untouched — zero errors, observations exactly monotonic — and
    (c) the returning arc's clients re-warm no faster than a fresh id."""
    import threading

    from byzantinemomentum_tpu.serve.fleet.local import (ask_socket,
                                                         fleet_socket)

    shards = fleet.shards
    victim = shards[0]
    v_base = next(f"vic{k}" for k in range(10_000)
                  if fleet.owner(f"vic{k}") == victim)
    s_base = next(f"sur{k}" for k in range(10_000)
                  if fleet.owner(f"sur{k}") != victim)

    def ask(base):
        return fleet.ask(_fleet_payloads([base], n, d, f, gar, rng)[0])

    for _ in range(3):
        before_v = ask(v_base)["verdicts"][v_base]["observations"]
        before_s = ask(s_base)["verdicts"][s_base]["observations"]
    fleet.kill(victim)
    # The parked line: routed to the dead arc, it must wait out the
    # outage and complete after the restart (exactly one disposition)
    parked = {}

    def park():
        sock, files = fleet_socket("127.0.0.1", fleet.port, timeout=60)
        try:
            parked["reply"] = ask_socket(
                files, _fleet_payloads([v_base], n, d, f, gar, rng)[0])
        finally:
            sock.close()

    parker = threading.Thread(target=park, name="loadgen-parked",
                              daemon=True)
    parker.start()
    # Survivor traffic rides through the outage untouched
    outage_errors = 0
    for _ in range(5):
        reply = ask(s_base)
        if not reply.get("ok"):
            outage_errors += 1
        else:
            after_s = reply["verdicts"][s_base]["observations"]
    fleet.restart(victim)
    parker.join(timeout=60)
    parked_reply = parked.get("reply") or {"ok": False}
    rewarm = (parked_reply["verdicts"][v_base]["observations"]
              if parked_reply.get("ok") else None)
    fresh_base = next(f"fr{k}" for k in range(10_000)
                      if fleet.owner(f"fr{k}") == victim)
    fresh = ask(fresh_base)["verdicts"][fresh_base]["observations"]
    return {
        "killed": victim,
        "on_dead": fleet.router.on_dead,
        "parked_line_recovered": bool(parked_reply.get("ok")),
        "survivor_errors": outage_errors,
        "survivor_observations": {"before": before_s, "after": after_s},
        "survivor_monotonic": bool(after_s == before_s + 5 - outage_errors),
        "rewarm_observations": rewarm,
        "fresh_observations": fresh,
        "rewarm_no_faster_than_fresh": bool(rewarm == fresh),
        "victim_observations_before_kill": before_v,
    }


def run_fleet(*, shard_counts=(1, 2, 4), scenarios=FLEET_SCENARIOS,
              requests=240, population=64, n=5, d=64, f=1, gar="median",
              max_batch=8, max_delay_ms=2.0, connections=8, seed=1,
              vnodes=None, recovery=True, router=None):
    """The sharded-fleet measurement: each named scenario driven through
    a real `FleetRouter` TCP front door at each shard count, plus the
    kill-safe failover round at the largest fleet. Returns the
    `BENCH_serve_fleet.json` payload (`"kind": "serve_fleet"`).

    Shards are IN-PROCESS (`serve/fleet/local.py`): real router, real
    per-shard sockets and stores — everything the router path measures —
    without N jax processes fighting for this host's cores (on the
    1-core CI box a subprocess fleet measures the OS scheduler, not the
    router; the artifact stamps `host_cores` so `bench_compare` can
    refuse cross-host comparisons). The subprocess launcher path is
    covered by the slow test tier (`tests/test_fleet.py`) instead.
    `router="host:port"` drives an EXTERNAL, already-running fleet
    (`python -m byzantinemomentum_tpu.serve.fleet`) and skips the
    in-process builds and the recovery round."""
    import os

    import jax

    from byzantinemomentum_tpu.serve.fleet.local import LocalFleet
    from byzantinemomentum_tpu.serve.fleet.ring import DEFAULT_VNODES

    vnodes = DEFAULT_VNODES if vnodes is None else int(vnodes)
    old_switch = sys.getswitchinterval()
    sys.setswitchinterval(0.001)
    rng = np.random.default_rng(seed)
    scenario_rows = {name: {} for name in scenarios}
    recovery_block = None
    spread = None
    try:
        if router is not None:
            host, port = router.rsplit(":", 1)
            for name in scenarios:
                bases = _scenario_bases(name, requests, population, rng)
                payloads = _fleet_payloads(bases, n, d, f, gar, rng)
                wall, lat, errors, extra = _drive_scenario(
                    name, host, int(port), payloads, connections)
                scenario_rows[name]["external"] = {
                    "agg_per_sec": round(len(lat) / max(wall, 1e-9), 2),
                    "errors": errors, **percentiles(lat), **extra}
            shard_counts = ()
        for shards in shard_counts:
            with LocalFleet(shards, vnodes=vnodes, router_server=True,
                            service={"max_batch": max_batch,
                                     "max_delay_ms": max_delay_ms}) \
                    as fleet:
                for svc in fleet.services.values():
                    svc.warmup([(gar, n, f, d, True)])
                for name in scenarios:
                    bases = _scenario_bases(name, requests, population,
                                            rng)
                    payloads = _fleet_payloads(bases, n, d, f, gar, rng)
                    wall, lat, errors, extra = _drive_scenario(
                        name, "127.0.0.1", fleet.port, payloads,
                        connections)
                    scenario_rows[name][str(shards)] = {
                        "agg_per_sec": round(len(lat) / max(wall, 1e-9),
                                             2),
                        "errors": errors, **percentiles(lat), **extra}
                if shards == max(shard_counts):
                    ring = fleet.membership.ring()
                    spread = ring.spread(
                        _scenario_bases("rotation", 4096, 4096, rng))
                    if recovery:
                        recovery_block = _fleet_recovery(
                            fleet, n=n, d=d, f=f, gar=gar, rng=rng)
    finally:
        sys.setswitchinterval(old_switch)

    def _rate(name, count):
        row = scenario_rows.get(name, {}).get(str(count))
        return row["agg_per_sec"] if row else None

    counts = sorted(int(c) for c in
                    next(iter(scenario_rows.values()), {})
                    if c != "external") if scenario_rows else []
    speedup = None
    if counts and len(counts) > 1:
        lo, hi = _rate(scenarios[0], counts[0]), _rate(scenarios[0],
                                                       counts[-1])
        if lo and hi:
            speedup = round(hi / lo, 3)
    return {
        "kind": "serve_fleet",
        "backend": jax.default_backend(),
        "host_cores": os.cpu_count(),
        "isolation": "external" if router is not None else "in_process",
        "config": {"requests": requests, "population": population,
                   "n": n, "d": d, "f": f, "gar": gar,
                   "max_batch": max_batch, "max_delay_ms": max_delay_ms,
                   "connections": connections, "seed": seed,
                   "vnodes": vnodes,
                   "shard_counts": list(shard_counts) or ["external"]},
        "ring": ({"vnodes": vnodes,
                  "spread_4096_keys": {k: int(v)
                                       for k, v in sorted(spread.items())}}
                 if spread else None),
        "scenarios": scenario_rows,
        "recovery": recovery_block,
        "fleet_speedup": speedup,
    }


def run_router_trace(*, requests=160, population=32, n=5, d=64, f=1,
                     gar="median", max_batch=8, max_delay_ms=2.0, seed=1,
                     tile_tolerance=0.15):
    """The router-path attribution block for `ATTRIB_serve.json`
    (`--trace`): a 2-shard in-process fleet, every line traced through
    the router's two legs — `route` (parse + ring lookup) and
    `shard_rtt` (queue wait + forward + the shard's whole service
    time). The legs are contiguous, so their sum must tile the
    client-measured request wall within `tile_tolerance` (what the
    client additionally pays over the router's recv→reply is one socket
    hop — if the tiling drifts past that, the router is spending time
    nobody attributed)."""
    from byzantinemomentum_tpu.serve.fleet.local import (LocalFleet,
                                                         ask_socket,
                                                         fleet_socket)

    rng = np.random.default_rng(seed)
    with LocalFleet(2, router_server=True,
                    service={"max_batch": max_batch,
                             "max_delay_ms": max_delay_ms}) as fleet:
        for svc in fleet.services.values():
            svc.warmup([(gar, n, f, d, True)])
        bases = _scenario_bases("rotation", requests, population, rng)
        payloads = _fleet_payloads(bases, n, d, f, gar, rng)
        sock, files = fleet_socket("127.0.0.1", fleet.port, timeout=120)
        walls = []
        try:
            for payload in payloads:
                t0 = time.perf_counter()
                reply = ask_socket(files, payload)
                walls.append((time.perf_counter() - t0) * 1000.0)
                if not reply.get("ok"):
                    raise RuntimeError(f"router trace request failed: "
                                       f"{reply}")
        finally:
            sock.close()
        spans = fleet.router.trace_spans()

    route = [s[0] for s in spans]
    shard_rtt = [s[1] for s in spans]
    total = [s[2] for s in spans]
    span_sum_mean = (sum(route) + sum(shard_rtt)) / max(len(spans), 1)
    wall_mean = float(np.mean(walls))
    tile_error = abs(span_sum_mean - wall_mean) / max(wall_mean, 1e-9)

    def dist(values):
        return {**percentiles(values),
                "max_ms": round(float(np.max(values)), 3)}

    return {
        "shards": 2,
        "requests": len(spans),
        "phases": {"route": dist(route), "shard_rtt": dist(shard_rtt)},
        "total": dist(total),
        "client_wall": dist(walls),
        "tile": {
            "span_sum_mean_ms": round(span_sum_mean, 4),
            "client_wall_mean_ms": round(wall_mean, 4),
            "error_frac": round(tile_error, 4),
            "within_tolerance": bool(tile_error <= tile_tolerance),
            "tolerance": tile_tolerance,
        },
    }


def _joined_hop_rows(records):
    """Aggregate joined trace records into per-hop distributions plus
    the per-record tiling error against the router-measured wall."""
    hops = {}
    tile_errors = []
    critical = {}
    for record in records:
        spans = record.get("spans_ms") or {}
        for hop, ms in spans.items():
            hops.setdefault(hop, []).append(float(ms))
        total = float(record.get("total_ms") or 0.0)
        if total > 0.0:
            tile_errors.append(abs(sum(spans.values()) - total) / total)
        hop = record.get("dominant")
        if hop:
            critical[hop] = critical.get(hop, 0) + 1
    return hops, tile_errors, critical


def _queue_wait_by_shard(records):
    """Per-arc `shard_queue` p99 over joined records — the cross-arc
    skew view where a zipf convoy shows up (the hot key's owner builds
    queue wait the other arcs never see)."""
    by_shard = {}
    for record in records:
        shard = record.get("shard")
        queue_ms = (record.get("spans_ms") or {}).get("shard_queue")
        if shard is not None and queue_ms is not None:
            by_shard.setdefault(shard, []).append(float(queue_ms))
    if not by_shard:
        return None
    p99 = {shard: round(float(np.percentile(values, 99)), 4)
           for shard, values in sorted(by_shard.items())}
    ordered = sorted(p99.values())
    return {"per_shard_p99_ms": p99,
            "counts": {shard: len(values)
                       for shard, values in sorted(by_shard.items())},
            "max_over_min": round(ordered[-1] / max(ordered[0], 1e-6), 3),
            "max_over_median": round(
                ordered[-1] / max(ordered[len(ordered) // 2], 1e-6), 3)}


def run_fleet_trace(*, shard_counts=(1, 2, 4), scenarios=FLEET_SCENARIOS,
                    requests=240, population=64, n=5, d=64, f=1,
                    gar="median", max_batch=8, max_delay_ms=2.0,
                    connections=8, seed=1, overhead_pairs=4,
                    tile_tolerance=0.15, overhead_bound=0.03):
    """Fleet-scope attribution mode (`--fleet --trace`): the
    `ATTRIB_serve_fleet.json` payload (`"kind":
    "serve_fleet_attribution"`).

    Every scenario × shard count drives a real router front door with
    the cross-process span JOIN on: each reply's shard trace record is
    spliced under the router envelope (`join_shard_trace`), so the
    per-hop columns — route, wire residual, SHARD QUEUE WAIT (its own
    column at last — the zipf convoy's home), pack, dispatch, device,
    resolve — come from joined records, not single-process proxies.
    Three checks ride along: (1) per-record tiling — the joined spans
    must sum to the router-measured client wall within
    `tile_tolerance`; (2) the paired tracing on/off overhead of the
    WHOLE plane (shard stamps + wire record + router splice) under
    `overhead_bound`; (3) the zipf convoy must be VISIBLE as cross-arc
    `shard_queue` p99 skew at the largest fleet."""
    import os
    import statistics

    import jax

    from byzantinemomentum_tpu.serve.fleet.local import LocalFleet

    old_switch = sys.getswitchinterval()
    sys.setswitchinterval(0.001)
    rng = np.random.default_rng(seed)
    scenario_rows = {name: {} for name in scenarios}
    zipf_skew = None
    ring_buffer = max(1024, 2 * requests)
    try:
        for shards in sorted(shard_counts):
            with LocalFleet(shards, router_server=True,
                            trace_buffer=ring_buffer,
                            service={"max_batch": max_batch,
                                     "max_delay_ms": max_delay_ms,
                                     "trace_buffer": ring_buffer}) \
                    as fleet:
                for svc in fleet.services.values():
                    svc.warmup([(gar, n, f, d, True)])
                for name in scenarios:
                    bases = _scenario_bases(name, requests, population,
                                            rng)
                    payloads = _fleet_payloads(bases, n, d, f, gar, rng)
                    before = fleet.router.joined_completed
                    wall, lat, errors, extra = _drive_scenario(
                        name, "127.0.0.1", fleet.port, payloads,
                        connections)
                    grown = fleet.router.joined_completed - before
                    records = (fleet.router.joined_records()[-grown:]
                               if grown else [])
                    hops, tile_errors, critical = _joined_hop_rows(
                        records)
                    tile_mean = (float(np.mean(tile_errors))
                                 if tile_errors else None)
                    row = {
                        "traced": len(records),
                        "errors": errors,
                        "agg_per_sec": round(
                            len(lat) / max(wall, 1e-9), 2),
                        "client_wall": percentiles(lat) if lat else None,
                        "hops": {hop: {**percentiles(values),
                                       "max_ms": round(
                                           float(np.max(values)), 3)}
                                 for hop, values in sorted(hops.items())},
                        "tile": {
                            "error_frac_mean": (round(tile_mean, 4)
                                                if tile_mean is not None
                                                else None),
                            "within_tolerance": bool(
                                tile_mean is not None
                                and tile_mean <= tile_tolerance),
                            "tolerance": tile_tolerance,
                        },
                        "critical_path": dict(sorted(
                            critical.items(), key=lambda kv: -kv[1])),
                    }
                    skew = (_queue_wait_by_shard(records)
                            if shards > 1 else None)
                    if skew is not None:
                        row["queue_wait_skew"] = skew
                    if (name == "zipf" and shards == max(shard_counts)
                            and skew is not None):
                        zipf_skew = {"shards": shards, **skew}
                    scenario_rows[name][str(shards)] = row

        # Paired on/off overhead of the WHOLE tracing plane (shard
        # stamps + wire trace record + router-side splice), measured on
        # its own fleet at the canonical 2-shard point: interleaved
        # on/off/off/on closed-loop windows, median of per-pair ratios
        overhead_shards = min(2, max(shard_counts))
        with LocalFleet(overhead_shards, router_server=True,
                        trace_buffer=ring_buffer,
                        service={"max_batch": max_batch,
                                 "max_delay_ms": max_delay_ms}) as fleet:
            for svc in fleet.services.values():
                svc.warmup([(gar, n, f, d, True)])

            def window(count=max(60, requests // 4)):
                bases = _scenario_bases("rotation", count, population,
                                        rng)
                payloads = _fleet_payloads(bases, n, d, f, gar, rng)
                wall, lat, errors, _ = _drive_scenario(
                    "rotation", "127.0.0.1", fleet.port, payloads,
                    connections)
                if errors:
                    raise RuntimeError(
                        f"overhead window saw {errors} errors")
                return len(lat) / max(wall, 1e-9)

            window(40)  # warm the measurement path itself
            ratios, on_rates, off_rates = [], [], []
            for _ in range(overhead_pairs):
                fleet.set_tracing(True)
                a_on = window()
                fleet.set_tracing(False)
                a_off = window()
                b_off = window()
                fleet.set_tracing(True)
                b_on = window()
                ratios.append((a_on + b_on) / (a_off + b_off))
                on_rates += [a_on, b_on]
                off_rates += [a_off, b_off]
            overhead = max(0.0, 1.0 - statistics.median(ratios))
    finally:
        sys.setswitchinterval(old_switch)

    return {
        "kind": "serve_fleet_attribution",
        "backend": jax.default_backend(),
        "host_cores": os.cpu_count(),
        "isolation": "in_process",
        "config": {"requests": requests, "population": population,
                   "n": n, "d": d, "f": f, "gar": gar,
                   "max_batch": max_batch,
                   "max_delay_ms": max_delay_ms,
                   "connections": connections, "seed": seed,
                   "shard_counts": sorted(shard_counts)},
        "tile_tolerance": tile_tolerance,
        "scenarios": scenario_rows,
        "zipf_queue_skew": zipf_skew,
        "overhead": {
            "pairs": overhead_pairs,
            "shards": overhead_shards,
            "agg_per_sec_tracing_on": round(max(on_rates), 2),
            "agg_per_sec_tracing_off": round(max(off_rates), 2),
            "ratio_median": round(statistics.median(ratios), 4),
            "frac": round(overhead, 4),
            "bound_frac": overhead_bound,
            "within_bound": bool(overhead <= overhead_bound),
        },
    }


def _scenario_cell(service, name, requests, population, n, d, f, gar,
                   rng):
    """One single-process scenario cell (r18): the `--fleet` population
    scenarios (`FLEET_SCENARIOS`) driven straight through
    `service.submit` — the SAME key streams (`_scenario_bases`), no
    router or socket in the path, so a regression in one of these cells
    is the engine itself (suspicion-store growth, admission, batcher
    fill under churn/skew), not the fleet plumbing. Each request's
    cohort is keyed by its scenario base id, batch-mates riding along
    as `{base}.{j}`. flash = closed-loop trickle of the first quarter,
    then the remainder as one saturation burst (the arrival stress,
    same keys)."""
    bases = _scenario_bases(name, requests, population, rng)
    jobs = [(cohort, [base] + [f"{base}.{j}" for j in range(1, n)])
            for cohort, base in zip(_cohorts(rng, requests, n, d), bases)]
    trickle = jobs[:max(1, requests // 4)] if name == "flash" else []
    burst = jobs[len(trickle):]
    latencies = []
    t0 = time.perf_counter()
    for cohort, ids in trickle:
        result = _submit(service, cohort, gar, f, ids).result(timeout=120)
        latencies.append(result.latency_ms)
    futures = [_submit(service, cohort, gar, f, ids)
               for cohort, ids in burst]
    latencies += [fut.result(timeout=120).latency_ms for fut in futures]
    wall = time.perf_counter() - t0
    return {"agg_per_sec": round(len(jobs) / wall, 2),
            "population": population, **percentiles(latencies)}


def _run_loadgen(requests, n, d, f, gar, max_batch, max_delay_ms, rate,
                 seed, repeats, AggregationService, backend,
                 population=64):
    rng = np.random.default_rng(seed)
    clients = tuple(f"client-{i}" for i in range(n))
    cells = [(gar, n, f, d, True)]

    # Baseline: single-request dispatch — its own service so max_batch=1
    # really means one program per request
    with AggregationService(max_batch=1, max_delay_ms=0.0) as seq:
        seq.warmup(cells, batch_sizes=(1,))
        sequential = _best([
            _sequential(seq, _cohorts(rng, requests, n, d), gar, f, clients)
            for _ in range(repeats)])

    with AggregationService(max_batch=max_batch,
                            max_delay_ms=max_delay_ms) as service:
        service.warmup(cells)
        # The "one program execution" term of the p99 bound, measured as
        # a real serving turnaround: a full burst flushes immediately
        # (no max-delay wait), so its worst per-request latency is
        # pack + dispatch + device + resolve + verdicts — everything a
        # request pays besides waiting for batch-mates
        turnarounds = []
        for _ in range(40):
            burst = [_submit(service, c, gar, f, clients)
                     for c in _cohorts(rng, max_batch, n, d)]
            turnarounds.append(max(fut.result(timeout=60).latency_ms
                                   for fut in burst))
        # Bounding a p99 needs the execution term at ITS p99, not its
        # median — the tail of a single batch turnaround (resolver
        # scheduling, an occasional allocator stall) is part of "one
        # program execution" as a request actually experiences it
        exec_ms = float(np.percentile(turnarounds, 99))

        batched = _best([
            _saturation(service, _cohorts(rng, requests, n, d), gar, f,
                        clients)
            for _ in range(repeats)])
        if rate is None:
            # The latency probe runs at HALF the measured capacity: high
            # enough that batching is active, low enough that queueing
            # delay (which any utilization > ~70% adds on top of the
            # max-delay + one-execution bound) stays out of the p99
            rate = max(1.0, 0.5 * batched["agg_per_sec"])
        open_loop = _open_loop(service, _cohorts(rng, requests, n, d),
                               gar, f, clients, rate, rng)
        # The PR 16/17 population scenarios through the single-process
        # engine: compare_serve gates these cells like any other once a
        # baseline artifact carries them
        scenario_cells = {
            f"serve.{name}": _scenario_cell(service, name, requests,
                                            population, n, d, f, gar, rng)
            for name in FLEET_SCENARIOS}
        stats = service.stats()

    speedup = round(batched["agg_per_sec"]
                    / max(sequential["agg_per_sec"], 1e-9), 2)
    p99_bound = round(max_delay_ms + exec_ms, 3)
    return {
        "kind": "serve",
        "backend": backend,
        "config": {"requests": requests, "n": n, "d": d, "f": f,
                   "gar": gar, "max_batch": max_batch,
                   "max_delay_ms": max_delay_ms, "seed": seed},
        "cells": {
            "serve.sequential": sequential,
            "serve.batched": batched,
            "serve.open_loop": open_loop,
            **scenario_cells,
        },
        "speedup_batched_vs_sequential": speedup,
        "exec_ms": round(exec_ms, 3),
        "p99_bound_ms": p99_bound,
        "p99_within_bound": bool(open_loop["p99_ms"] <= p99_bound),
        "stats": stats,
    }


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="serve_loadgen",
        description="Open-loop Poisson load generator for the aggregation "
                    "service; writes BENCH_serve.json")
    parser.add_argument("--requests", type=int, default=400)
    parser.add_argument("--n", type=int, default=11,
                        help="cohort rows per request")
    parser.add_argument("--d", type=int, default=128,
                        help="submission dimension")
    parser.add_argument("--f", type=int, default=2)
    parser.add_argument("--gar", default="krum")
    parser.add_argument("--max-batch", type=int, default=8)
    parser.add_argument("--max-delay-ms", type=float, default=5.0)
    parser.add_argument("--rate", type=float, default=None,
                        help="open-loop arrival rate per second "
                             "(default: 50%% of measured capacity)")
    parser.add_argument("--repeats", type=int, default=2,
                        help="throughput windows per phase (best kept)")
    parser.add_argument("--seed", type=int, default=1)
    parser.add_argument("--out", default=None,
                        help="artifact path (default: BENCH_serve.json at "
                             "the repo root; ATTRIB_serve.json under "
                             "--trace)")
    parser.add_argument("--smoke", action="store_true",
                        help="tiny CI-sized run (mechanics proof, not a "
                             "measurement); no artifact unless --out-smoke")
    parser.add_argument("--out-smoke", action="store_true",
                        help="write the artifact even under --smoke")
    parser.add_argument("--no-heterogeneous", action="store_true",
                        help="skip the mixed-(n, d) workload phase")
    parser.add_argument("--trace", action="store_true",
                        help="trace-collection mode: per-phase serve "
                             "attribution + tracing overhead, written as "
                             "ATTRIB_serve.json (obs/trace); includes the "
                             "2-shard router attribution block")
    parser.add_argument("--metrics-overhead", action="store_true",
                        help="metrics-plane overhead mode: paired "
                             "registry-on/registry-off saturation windows "
                             "(two services — the registry is bound at "
                             "construction), written as BENCH_metrics.json "
                             "with the 2%% acceptance bound")
    parser.add_argument("--overhead-bound", type=float, default=0.02,
                        help="acceptance ceiling for --metrics-overhead "
                             "(fraction of agg/s; default 0.02)")
    parser.add_argument("--fleet", action="store_true",
                        help="sharded-fleet mode: scenario traffic through "
                             "a consistent-hash router at each --shards "
                             "count + the kill-safe failover round, written "
                             "as BENCH_serve_fleet.json")
    parser.add_argument("--shards", default="1,2,4",
                        help="comma-separated shard counts for --fleet "
                             "(default 1,2,4)")
    parser.add_argument("--router", default=None, metavar="HOST:PORT",
                        help="with --fleet: drive an EXTERNAL running "
                             "fleet (python -m byzantinemomentum_tpu"
                             ".serve.fleet) instead of in-process shards; "
                             "skips the recovery round")
    parser.add_argument("--population", type=int, default=64,
                        help="distinct routing keys per scenario (--fleet "
                             "and the single-process scenario cells)")
    parser.add_argument("--connections", type=int, default=8,
                        help="closed-loop client connections for --fleet")
    args = parser.parse_args(argv)

    if args.metrics_overhead:
        kwargs = dict(requests=args.requests, n=args.n, d=args.d,
                      f=args.f, gar=args.gar, max_batch=args.max_batch,
                      max_delay_ms=args.max_delay_ms, seed=args.seed,
                      bound_frac=args.overhead_bound)
        if args.smoke:
            kwargs.update(requests=min(args.requests, 120),
                          d=min(args.d, 64), overhead_pairs=2)
        payload = run_metrics_overhead(**kwargs)
        if args.smoke:
            payload["smoke"] = True
        line = {k: payload[k] for k in
                ("kind", "backend", "agg_per_sec_metrics_on",
                 "agg_per_sec_metrics_off", "overhead_frac",
                 "bound_frac", "within_bound")}
        line["metered"] = len(payload["registry_sample"]["names"])
        print(json.dumps(line))
        if not args.smoke or args.out_smoke:
            out = pathlib.Path(args.out) if args.out \
                else ROOT / "BENCH_metrics.json"
            out.write_text(json.dumps(payload, indent=2) + "\n")
            print(f"serve_loadgen: wrote {out}")
        return 0

    if args.fleet and args.trace:
        # Fleet-scope attribution: cross-process span join per scenario
        # x shard count -> ATTRIB_serve_fleet.json
        kwargs = dict(requests=args.requests, population=args.population,
                      n=args.n, d=args.d, f=args.f, gar=args.gar,
                      max_batch=args.max_batch,
                      max_delay_ms=args.max_delay_ms,
                      connections=args.connections, seed=args.seed,
                      shard_counts=tuple(int(c) for c in
                                         args.shards.split(",") if c))
        if args.smoke:
            kwargs.update(requests=min(args.requests, 60),
                          population=min(args.population, 16),
                          d=min(args.d, 64), overhead_pairs=2,
                          shard_counts=tuple(
                              c for c in kwargs["shard_counts"] if c <= 2)
                          or (1, 2))
        payload = run_fleet_trace(**kwargs)
        line = {k: payload[k] for k in ("kind", "backend", "host_cores",
                                        "isolation")}
        top = str(max(kwargs["shard_counts"]))
        line["tile_error_frac"] = {
            name: rows[top]["tile"]["error_frac_mean"]
            for name, rows in payload["scenarios"].items()}
        line["overhead_frac"] = payload["overhead"]["frac"]
        line["overhead_within_bound"] = payload["overhead"]["within_bound"]
        if payload["zipf_queue_skew"]:
            line["zipf_queue_skew_max_over_min"] = \
                payload["zipf_queue_skew"]["max_over_min"]
        print(json.dumps(line))
        if not args.smoke or args.out_smoke:
            out = pathlib.Path(args.out) if args.out \
                else ROOT / "ATTRIB_serve_fleet.json"
            out.write_text(json.dumps(payload, indent=2) + "\n")
            print(f"serve_loadgen: wrote {out}")
        return 0

    if args.fleet:
        kwargs = dict(requests=args.requests, population=args.population,
                      n=args.n, d=args.d, f=args.f, gar=args.gar,
                      max_batch=args.max_batch,
                      max_delay_ms=args.max_delay_ms,
                      connections=args.connections, seed=args.seed,
                      shard_counts=tuple(int(c) for c in
                                         args.shards.split(",") if c),
                      router=args.router)
        if args.smoke:
            kwargs.update(requests=min(args.requests, 60),
                          population=min(args.population, 16),
                          d=min(args.d, 64),
                          shard_counts=tuple(
                              c for c in kwargs["shard_counts"] if c <= 2)
                          or (1, 2))
        payload = run_fleet(**kwargs)
        line = {k: payload[k] for k in ("kind", "backend", "host_cores",
                                        "isolation", "fleet_speedup")}
        line["scenarios"] = {
            name: {count: row["agg_per_sec"]
                   for count, row in rows.items()}
            for name, rows in payload["scenarios"].items()}
        if payload["recovery"]:
            line["recovery"] = {k: payload["recovery"][k] for k in
                                ("killed", "parked_line_recovered",
                                 "survivor_errors", "survivor_monotonic",
                                 "rewarm_no_faster_than_fresh")}
        print(json.dumps(line))
        if not args.smoke or args.out_smoke:
            out = pathlib.Path(args.out) if args.out \
                else ROOT / "BENCH_serve_fleet.json"
            out.write_text(json.dumps(payload, indent=2) + "\n")
            print(f"serve_loadgen: wrote {out}")
        return 0

    if args.trace:
        kwargs = dict(requests=args.requests, n=args.n, d=args.d,
                      f=args.f, gar=args.gar, max_batch=args.max_batch,
                      max_delay_ms=args.max_delay_ms, rate=args.rate,
                      seed=args.seed)
        if args.smoke:
            kwargs.update(requests=min(args.requests, 120),
                          d=min(args.d, 64), overhead_pairs=2)
        payload = run_trace(**kwargs)
        payload["router"] = run_router_trace(
            requests=min(args.requests, 160) if args.smoke
            else max(args.requests // 2, 160),
            d=min(args.d, 64) if args.smoke else args.d,
            n=args.n, f=args.f, gar=args.gar, max_batch=args.max_batch,
            max_delay_ms=args.max_delay_ms, seed=args.seed)
        line = {k: payload[k] for k in ("kind", "backend")}
        line["phases_p50_ms"] = {name: cell["p50_ms"]
                                 for name, cell in payload["phases"].items()}
        line["tile"] = payload["tile"]
        line["overhead_frac"] = payload["overhead"]["frac"]
        line["router"] = {
            "route_p50_ms": payload["router"]["phases"]["route"]["p50_ms"],
            "shard_rtt_p50_ms":
                payload["router"]["phases"]["shard_rtt"]["p50_ms"],
            "tile": payload["router"]["tile"]}
        print(json.dumps(line))
        if not args.smoke or args.out_smoke:
            out = pathlib.Path(args.out) if args.out \
                else ROOT / "ATTRIB_serve.json"
            out.write_text(json.dumps(payload, indent=2) + "\n")
            print(f"serve_loadgen: wrote {out}")
        return 0

    kwargs = dict(requests=args.requests, n=args.n, d=args.d, f=args.f,
                  gar=args.gar, max_batch=args.max_batch,
                  max_delay_ms=args.max_delay_ms, rate=args.rate,
                  seed=args.seed, repeats=args.repeats,
                  heterogeneous=not args.no_heterogeneous,
                  population=args.population)
    if args.smoke:
        kwargs.update(requests=min(args.requests, 80), d=min(args.d, 64),
                      hetero_repeats=2,
                      population=min(args.population, 16))
    payload = run_loadgen(**kwargs)

    line = {k: payload[k] for k in ("kind", "backend",
                                    "speedup_batched_vs_sequential",
                                    "p99_bound_ms", "p99_within_bound")}
    line["cells"] = {name: {k: cell[k] for k in ("agg_per_sec", "p50_ms",
                                                 "p99_ms")}
                     for name, cell in payload["cells"].items()}
    if "compiles" in payload:
        line["compiles"] = payload["compiles"]
    print(json.dumps(line))
    if not args.smoke or args.out_smoke:
        out = pathlib.Path(args.out) if args.out \
            else ROOT / "BENCH_serve.json"
        out.write_text(json.dumps(payload, indent=2) + "\n")
        print(f"serve_loadgen: wrote {out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
