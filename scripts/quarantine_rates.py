#!/usr/bin/env python3
"""Calibrate the host-quarantine enter threshold
(`--quarantine-anomaly-polls`) from recorded anomaly/clear edge streams.

The straggler policy's quarantine arm (`cluster/straggler.py`) marks a
host SUSPECT after `anomaly_enter` consecutive launcher polls that saw
the heartbeat's `health.anomaly` flag up — one bad window is not a
verdict. The ROADMAP question behind that knob: how many polls does a
TRANSIENT anomaly (one the monitor itself clears — a spike that passed,
a baseline re-converging) stay visible for? Set the threshold below
that and every transient quarantines a healthy host; set it far above
and a genuinely sick host streams poisoned gradients for the whole
margin.

This script measures the transient side from recorded runs: the
`health_anomaly` / `health_cleared` edges a `HealthMonitor`
(`obs/health`) emitted are folded into monitor-level anomaly episodes
(the heartbeat flag is up while ANY channel is anomalous, so an episode
runs from the edge that raised the first channel to the clear that
dropped the last), split into CLEARED episodes (transients — the false
-positive pressure) and PERSISTENT ones (still open at end of stream —
what quarantine exists to catch). Durations are converted to launcher
polls at `--poll-interval`, and the recommended threshold is one poll
past the 95th percentile of the cleared episodes' spans: ~95% of
observed transients die out before the streak can fire (false-positive
rate <= 5%), while a persistent anomaly pays just one extra poll. The
cost per genuinely sick host (threshold x poll interval) is reported
next to the number so the trade is explicit.

Usage:
  python scripts/quarantine_rates.py RUN_DIR [RUN_DIR ...] [--json]

Each RUN_DIR is a run's result directory (its `telemetry.jsonl` holds
the monitor stream); a direct path to a telemetry .jsonl file works
too. Prints a human summary plus one parseable
`quarantine-rates: {...}` line; `cluster/straggler.py::
resolve_anomaly_polls` consumes the `--json` file directly
(`--quarantine-rates` on the cluster launcher).
"""

import argparse
import json
import math
import pathlib
import sys

ROOT = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(ROOT))

from byzantinemomentum_tpu.obs.recorder import load_records  # noqa: E402

__all__ = ["anomaly_episodes", "episode_polls", "recommend_polls",
           "recommendation", "summarize", "main"]

# The launcher's supervision poll interval (`--poll`): the clock the
# anomaly streak is counted on
DEFAULT_POLL_S = 0.2

# Never fire on a single anomalous poll, whatever the record says — the
# quarantine arm exists because one bad window is not a verdict
FLOOR_POLLS = 2

# The target false-positive rate: the threshold clears >= this fraction
# of observed transient episodes
FP_QUANTILE = 0.95


def anomaly_episodes(records):
    """Fold one telemetry stream into monitor-level anomaly episodes.

    Returns `{"cleared": [durations_s], "persistent": int}`: an episode
    opens at the `health_anomaly` edge that raised the FIRST anomalous
    channel (the heartbeat flag's rising edge) and closes at the
    `health_cleared` edge that dropped the LAST (the falling edge) —
    per-channel edges inside an open episode extend it, they don't
    nest. Episodes still open when the stream ends are PERSISTENT: the
    monitor never cleared them, so a quarantine streak of any length
    would (rightly) have caught them.
    """
    active = set()      # channels currently anomalous
    opened_at = None    # t of the flag's rising edge
    cleared = []
    persistent = 0
    for record in records:
        name = record.get("name")
        if record.get("kind") != "event" \
                or name not in ("health_anomaly", "health_cleared"):
            continue
        data = record.get("data") or {}
        channel, t = data.get("channel"), record.get("t")
        if channel is None or t is None:
            continue
        if name == "health_anomaly":
            if not active:
                opened_at = float(t)
            active.add(channel)
            continue
        active.discard(channel)
        if not active and opened_at is not None:
            cleared.append(max(0.0, float(t) - opened_at))
            opened_at = None
    if active and opened_at is not None:
        persistent += 1
    return {"cleared": sorted(cleared), "persistent": persistent}


def episode_polls(duration_s, poll_s):
    """Launcher polls a flag held up for `duration_s` spans: every poll
    inside the window sees it, and the edge poll that caught the rise
    counts too — the streak the quarantine arm would have accumulated."""
    return int(math.floor(max(0.0, duration_s) / max(poll_s, 1e-9))) + 1


def _percentile(values, q):
    """Nearest-rank percentile of a sorted list (None when empty)."""
    if not values:
        return None
    rank = max(1, math.ceil(q * len(values)))
    return values[rank - 1]


def _stats(values):
    if not values:
        return None
    return {"count": len(values),
            "min_s": round(values[0], 3),
            "median_s": round(_percentile(values, 0.5), 3),
            "p95_s": round(_percentile(values, 0.95), 3),
            "max_s": round(values[-1], 3)}


def recommend_polls(episodes, poll_s):
    """The enter-threshold recommendation from measured episodes.

    One poll past the p95 of cleared-episode spans when transients were
    observed — the streak ~95% of them cannot reach; with only
    persistent anomalies on record there is no false-positive pressure
    to calibrate against, so the floor applies (fast quarantine, zero
    observed transients sacrificed). None when the stream carries no
    episodes at all."""
    if episodes["cleared"]:
        p95 = _percentile(episodes["cleared"], FP_QUANTILE)
        return max(FLOOR_POLLS, episode_polls(p95, poll_s) + 1)
    if episodes["persistent"]:
        return FLOOR_POLLS
    return None


def recommendation(episodes, poll_s):
    """The machine-readable block `cluster/straggler.py::
    resolve_anomaly_polls` consumes: the threshold, WHAT it was derived
    from, and the evidence counts."""
    cleared = episodes["cleared"]
    if cleared:
        basis = f"fp_rate<={round(1.0 - FP_QUANTILE, 2)}"
    elif episodes["persistent"]:
        basis = "persistent_only_floor"
    else:
        basis = None
    polls = recommend_polls(episodes, poll_s)
    block = {"anomaly_polls": polls, "basis": basis,
             "cleared": len(cleared),
             "persistent": int(episodes["persistent"]),
             "poll_interval_s": poll_s}
    if cleared:
        block["p95_cleared_s"] = round(
            _percentile(cleared, FP_QUANTILE), 3)
    if polls is not None:
        block["cost_per_sick_host_s"] = round(polls * poll_s, 3)
    return block


def summarize(run_dirs, poll_s=DEFAULT_POLL_S):
    """The aggregate summary over one or more run directories (or
    direct telemetry file paths)."""
    merged = {"cleared": [], "persistent": 0}
    runs = 0
    for run in run_dirs:
        records = load_records(pathlib.Path(run))
        if not records:
            continue
        runs += 1
        episodes = anomaly_episodes(records)
        merged["cleared"].extend(episodes["cleared"])
        merged["persistent"] += episodes["persistent"]
    merged["cleared"].sort()
    polls = recommend_polls(merged, poll_s)
    return {
        "kind": "quarantine_rates",
        "runs": runs,
        "cleared_episodes": _stats(merged["cleared"]),
        "persistent_episodes": merged["persistent"],
        "poll_interval_s": poll_s,
        "recommended_anomaly_polls": polls,
        "recommendation": recommendation(merged, poll_s),
    }


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="quarantine_rates",
        description="Calibrate the quarantine enter threshold from "
                    "recorded health_anomaly/health_cleared edge "
                    "streams")
    parser.add_argument("runs", nargs="+",
                        help="run directories (or telemetry .jsonl "
                             "files) holding HealthMonitor anomaly/"
                             "clear events")
    parser.add_argument("--poll-interval", type=float,
                        default=DEFAULT_POLL_S,
                        help="launcher supervision poll interval in "
                             "seconds (the cluster launcher's --poll; "
                             f"default {DEFAULT_POLL_S})")
    parser.add_argument("--json", action="store_true",
                        help="print only the JSON summary line")
    args = parser.parse_args(argv)
    if args.poll_interval <= 0:
        parser.error(f"non-positive poll interval {args.poll_interval}")

    summary = summarize(args.runs, args.poll_interval)
    line = "quarantine-rates: " + json.dumps(summary, sort_keys=True)
    if args.json:
        print(line)
        return 0 if summary["runs"] else 1
    if not summary["runs"]:
        print("quarantine_rates: no telemetry records found under the "
              "given paths")
        return 1
    print(f"anomaly episodes over {summary['runs']} run(s):")
    stats = summary["cleared_episodes"]
    if stats is None:
        print("  cleared (transient)          (none observed)")
    else:
        print(f"  cleared (transient)          x{stats['count']}  "
              f"min {stats['min_s']}s  median {stats['median_s']}s  "
              f"p95 {stats['p95_s']}s  max {stats['max_s']}s")
    if summary["persistent_episodes"]:
        print(f"  persistent (never cleared)   "
              f"x{summary['persistent_episodes']}")
    rec = summary["recommendation"]
    if summary["recommended_anomaly_polls"] is None:
        print("  no anomaly episodes; no recommendation")
    else:
        print(f"  recommended enter threshold: "
              f"{summary['recommended_anomaly_polls']} polls at "
              f"{summary['poll_interval_s']}s ({rec['basis']}; a sick "
              f"host streams ~{rec['cost_per_sick_host_s']}s before "
              f"quarantine)")
    print(line)
    return 0


if __name__ == "__main__":
    sys.exit(main())
