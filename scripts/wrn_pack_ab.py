#!/usr/bin/env python3
"""A/B the WRN-28-10 packing escapes on device — prints ONE JSON line.

PERF_NOTES.md r5: the WRN cell's honest phase sits ~3.7x off its MXU
floor because S = 9 admits no worker packing (no divisor P of 9 makes
P*160 or P*320 lane-aligned). Two escapes exist, with opposite trades:

* `worker-pad`  (`BMT_WORKER_PAD=12`, engine-level): pad the sampled
  stack to S' = 12 so the existing worker packing engages (P = 4/2 for
  C = 160/320) — pays the 3 dummy workers' compute PLUS the
  block-diagonal zero FLOPs.
* `batch-pack`  (`BMT_BATCH_PACK=1`, `models/core.py`): concatenate Q
  batch items on the channel axis (Q = 4/2) — no dummy compute, the same
  zero-FLOP trade on the packed convs, but the sublane-resident batch
  axis shrinks B -> B/Q (pads back up toward the 8/16-row tile).

Whichever the chained device-time harness prefers is the one to set for
the cell (neither is a default until a device run lands the verdict —
this script IS that verdict's instrument). Measurement mechanics reuse
`bench.py::_run_mode` (depth-2 pipelined dispatch, finite-defense
assertions, logical-FLOP MFU), so steps/s here are directly comparable
to the BENCH cell numbers.

Usage:
  python scripts/wrn_pack_ab.py [--modes baseline,worker-pad,batch-pack]
                                [--dtypes f32,bf16] [--smoke] [--out F]

`--smoke` shrinks the cell (tiny WRN, few steps) so CI can prove the
harness end to end off-TPU; the JSON carries `"backend"`/`"smoke"`
markers and the INCOMPARABLE discipline applies downstream.
"""

import argparse
import json
import os
import pathlib
import sys

ROOT = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(ROOT))

# The escape knobs are read at TRACE time — each mode below sets its
# environment before the engine builds, which is why every measurement
# constructs a fresh engine via bench._run_mode.
MODES = {
    "baseline": {},
    "worker-pad": {"BMT_WORKER_PAD": "12"},
    "batch-pack": {"BMT_BATCH_PACK": "1"},
}
_KNOBS = ("BMT_WORKER_PAD", "BMT_BATCH_PACK")


def _cell_kwargs(smoke):
    if smoke:
        return dict(gar_name="bulyan", n=11, f=2,
                    model="wide_resnet-Wide_ResNet",
                    model_args={"depth": 10, "widen_factor": 1,
                                "dropout_rate": 0.3, "num_classes": 10},
                    loss="crossentropy", nesterov=True,
                    windows=1, min_measure_s=0.1)
    return dict(gar_name="bulyan", n=11, f=2,
                model="wide_resnet-Wide_ResNet",
                model_args={"depth": 28, "widen_factor": 10,
                            "dropout_rate": 0.3, "num_classes": 10},
                loss="crossentropy", nesterov=True,
                windows=1, min_measure_s=2.5)


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="wrn_pack_ab",
        description="Chained device-time A/B of the WRN packing escapes")
    parser.add_argument("--modes", default="baseline,worker-pad,batch-pack",
                        help="comma list from: " + ",".join(MODES))
    parser.add_argument("--dtypes", default="f32,bf16",
                        help="comma list from: f32,bf16")
    parser.add_argument("--smoke", action="store_true",
                        help="tiny cell, short windows (CI harness proof)")
    parser.add_argument("--out", default=None,
                        help="also write the JSON line to this path")
    args = parser.parse_args(argv)

    modes = [m.strip() for m in args.modes.split(",") if m.strip()]
    unknown = [m for m in modes if m not in MODES]
    if unknown:
        parser.error(f"unknown mode(s) {unknown}; choose from {list(MODES)}")
    dtypes = [d.strip() for d in args.dtypes.split(",") if d.strip()]
    if not set(dtypes) <= {"f32", "bf16"}:
        parser.error("dtypes must be from: f32,bf16")

    import bench  # noqa: E402  (repo-root module; sys.path above)
    from byzantinemomentum_tpu import data  # noqa: E402
    from byzantinemomentum_tpu.data.device import DeviceData  # noqa: E402

    backend = bench._ensure_backend()
    if args.smoke:
        # The smoke proves the harness end to end, not the numbers: on a
        # 1-core CI host the real measurement loop (M=20 programs, 400
        # steps) would take tens of minutes per mode
        bench.STEPS_PER_PROGRAM = 2
        bench.WARMUP_STEPS = 1
        bench.MAX_MEASURE_STEPS = 4
    batch = 4 if args.smoke else 20
    trainset, _ = data.make_datasets("cifar10", batch, batch, seed=0)
    train_data = DeviceData(trainset)
    cell = _cell_kwargs(args.smoke)

    results = {}
    saved = {k: os.environ.get(k) for k in _KNOBS}
    try:
        for mode in modes:
            for knob in _KNOBS:
                os.environ.pop(knob, None)
            os.environ.update(MODES[mode])
            per_dtype = {}
            for dtype in dtypes:
                compute = None if dtype == "f32" else "bfloat16"
                sps, flops = bench._run_mode(compute, train_data, **cell)
                per_dtype[dtype] = {"steps_per_sec": sps,
                                    "flops_per_step": flops}
            results[mode] = per_dtype
    finally:
        for knob, value in saved.items():
            if value is None:
                os.environ.pop(knob, None)
            else:
                os.environ[knob] = value

    best = max(
        ((mode, dtype, v["steps_per_sec"])
         for mode, per in results.items() for dtype, v in per.items()),
        key=lambda t: t[2])
    payload = {
        "kind": "wrn_pack_ab",
        "backend": backend,
        "smoke": bool(args.smoke),
        "cell": {k: cell[k] for k in ("gar_name", "n", "f")}
        | {"batch": batch, "model_args": cell["model_args"]},
        "results": results,
        "preferred": {"mode": best[0], "dtype": best[1],
                      "steps_per_sec": best[2]},
    }
    line = json.dumps(payload)
    if args.out:
        pathlib.Path(args.out).write_text(line + "\n")
    print(line)
    return 0


if __name__ == "__main__":
    sys.exit(main())
