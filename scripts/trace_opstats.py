#!/usr/bin/env python3
"""Aggregate a jax.profiler xplane trace into per-HLO-op device times.

The TPU-side complement of `scripts/mfu_breakdown.py` (see PERF_NOTES.md):
capture a trace of the program under study, then attribute device time to
individual fusions/ops —

    import jax
    jax.profiler.start_trace("/tmp/my_trace")
    ...run the program a few times...
    jax.profiler.stop_trace()
    python scripts/trace_opstats.py /tmp/my_trace --steps 60

`--steps` divides the totals so the numbers read as ms/step (pass the
number of training steps the traced region executed).

This is a thin CLI over `byzantinemomentum_tpu/obs/attrib/xplane.py` —
the parsing core lives there so the `--attribution` pipeline and this
script cannot drift apart; the pure-python protobuf forcing (the
tensorboard profile plugin's converter is broken against this image's TF
build) stays here, in this CLI's own process, as it always did. CPU
traces parse too (`--device` defaults to the first TPU plane; pass e.g.
`--device /host:CPU` or leave it to the library's auto-detection with
`--device auto`).

Usage: python scripts/trace_opstats.py <trace_dir> [--steps N] [--top K]
"""

import argparse
import os
import pathlib
import sys

# The original workaround, kept for this CLI's own process: the
# tensorboard profile plugin's converter is broken against this image's
# TF build, and the pure-python backend is the known-safe parse path
# (must be set before any protobuf import; export the var yourself — e.g.
# to "upb" — to prefer the ~35x faster default backend on big traces)
os.environ.setdefault("PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION", "python")

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from byzantinemomentum_tpu.obs.attrib import xplane  # noqa: E402


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("trace_dir", help="directory passed to start_trace")
    parser.add_argument("--steps", type=int, default=1,
                        help="training steps executed in the traced region "
                             "(divides totals into ms/step)")
    parser.add_argument("--top", type=int, default=30)
    parser.add_argument("--device", default="/device:TPU:0",
                        help="plane name (default the first TPU core; "
                             "'auto' lets the library pick the device "
                             "planes — the /host:CPU executor lines on the "
                             "CPU backend)")
    args = parser.parse_args()

    try:
        space = xplane.load_xspace(args.trace_dir)
    except FileNotFoundError as err:
        sys.exit(str(err))

    planes = None if args.device == "auto" else args.device
    if planes is not None and not any(
            planes in p.name for p in space.planes):
        sys.exit(f"plane {args.device!r} not in trace; available: "
                 f"{sorted(p.name for p in space.planes)}")
    totals = xplane.aggregate_ops(space, planes=planes)
    if not totals:
        sys.exit(f"no HLO op events on plane(s) {args.device!r} — "
                 f"try '--device auto'")

    total = sum(ms for ms, _ in totals.values())
    print(f"total op time {total:.1f} ms "
          f"({total / args.steps:.3f} ms/step over {args.steps} steps); "
          f"top {args.top}:")
    ranked = sorted(totals.items(), key=lambda kv: -kv[1][0])
    for name, (ms, count) in ranked[:args.top]:
        print(f"{ms / args.steps:9.4f} ms/step  x{count:6d}  {name[:110]}")


if __name__ == "__main__":
    main()
