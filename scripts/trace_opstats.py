#!/usr/bin/env python3
"""Aggregate a jax.profiler xplane trace into per-HLO-op device times.

The TPU-side complement of `scripts/mfu_breakdown.py` (see PERF_NOTES.md):
capture a trace of the program under study, then attribute device time to
individual fusions/ops —

    import jax
    jax.profiler.start_trace("/tmp/my_trace")
    ...run the program a few times...
    jax.profiler.stop_trace()
    python scripts/trace_opstats.py /tmp/my_trace --steps 60

`--steps` divides the totals so the numbers read as ms/step (pass the
number of training steps the traced region executed). The tensorboard
profile plugin's converter is broken against this image's TF build; the
xplane proto that TF ships parses fine under the pure-python protobuf
backend, which this script forces for its own process.

Usage: python scripts/trace_opstats.py <trace_dir> [--steps N] [--top K]
"""

import argparse
import collections
import glob
import os
import sys

os.environ.setdefault("PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION", "python")


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("trace_dir", help="directory passed to start_trace")
    parser.add_argument("--steps", type=int, default=1,
                        help="training steps executed in the traced region "
                             "(divides totals into ms/step)")
    parser.add_argument("--top", type=int, default=30)
    parser.add_argument("--device", default="/device:TPU:0",
                        help="plane name (default the first TPU core)")
    args = parser.parse_args()

    from tensorflow.tsl.profiler.protobuf import xplane_pb2

    pattern = os.path.join(args.trace_dir, "plugins/profile/*/*.xplane.pb")
    paths = sorted(glob.glob(pattern))
    if not paths:
        sys.exit(f"no xplane.pb under {pattern!r} — did stop_trace() run?")
    space = xplane_pb2.XSpace()
    with open(paths[-1], "rb") as fd:
        space.ParseFromString(fd.read())

    planes = {p.name: p for p in space.planes}
    if args.device not in planes:
        sys.exit(f"plane {args.device!r} not in trace; available: "
                 f"{sorted(planes)}")
    plane = planes[args.device]
    meta = plane.event_metadata
    lines = {l.name: l for l in plane.lines}
    if "XLA Ops" not in lines:
        sys.exit(f"no 'XLA Ops' line; available: {sorted(lines)}")

    agg = collections.Counter()
    cnt = collections.Counter()
    for e in lines["XLA Ops"].events:
        name = meta[e.metadata_id].name
        agg[name] += e.duration_ps / 1e9  # -> ms
        cnt[name] += 1

    total = sum(agg.values())
    print(f"total op time {total:.1f} ms "
          f"({total / args.steps:.3f} ms/step over {args.steps} steps); "
          f"top {args.top}:")
    for name, ms in agg.most_common(args.top):
        print(f"{ms / args.steps:9.4f} ms/step  x{cnt[name]:6d}  {name[:110]}")


if __name__ == "__main__":
    main()
