#!/usr/bin/env python3
"""(Re)bless the StableHLO lowering goldens.

Writes `tests/goldens/lowerings.json`: one sha256 fingerprint of the
lowered StableHLO text per lattice cell — the enumeration is DERIVED
from the program builder (`analysis/lattice.py`): every GAR ×
{plain, diag, masked-quorum} kernel, their virtual-mesh sharded forms
(`jax.make_mesh` over CPU host devices), the serve-layer cell programs
and the donated update contract — plus the (jax version, backend)
coordinates the fingerprints are comparable under. The lint tier's gate
(`python -m byzantinemomentum_tpu.analysis --check-lowerings`) fails on
any unexplained change — run THIS script only when a lowering change is
intentional and reviewed, and commit the diff with the change that
caused it.

Cells the enumerator no longer produces are PRUNED (the file is the
enumeration, nothing else) and reported, so stale keys cannot linger.

Idempotent: blessing twice under one toolchain is byte-identical
(sorted keys, no timestamps).

Usage: python scripts/bless_lowerings.py [--out PATH] [--check]
"""

import argparse
import json
import os
import pathlib
import sys

ROOT = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(ROOT))

# Deterministic fingerprints need the CPU backend (this environment's
# sitecustomize may force a TPU platform; the config update after import
# is what actually sticks — see tests/conftest.py), and the virtual-mesh
# cells need multiple host devices — both must be set before backend init
os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from byzantinemomentum_tpu.analysis import lowering  # noqa: E402


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--out", type=str, default=None,
                        help="goldens path (default "
                             "tests/goldens/lowerings.json)")
    parser.add_argument("--check", action="store_true",
                        help="only report drift against the existing "
                             "goldens; do not rewrite")
    args = parser.parse_args()
    path = pathlib.Path(args.out) if args.out else lowering.GOLDENS_PATH

    if args.check:
        report = lowering.check(path)
        print(report)
        return 0 if report["status"] in ("ok", "incomparable") else 1

    before_bytes = path.read_bytes() if path.is_file() else None
    old_cells = {}
    if before_bytes is not None:
        try:
            old_cells = json.loads(before_bytes).get("cells", {})
        except ValueError:
            pass  # a corrupt goldens file is simply replaced
    out = lowering.bless(path)
    new = json.loads(out.read_text())
    changed = before_bytes != out.read_bytes()
    pruned = sorted(k for k in old_cells if k not in new["cells"])
    added = sorted(k for k in new["cells"] if k not in old_cells)
    print(f"blessed {len(new['cells'])} cells -> {out}"
          + (" (changed)" if changed else " (unchanged)"))
    if pruned:
        print(f"pruned {len(pruned)} stale cell(s) the enumerator no "
              f"longer produces:")
        for key in pruned:
            print(f"  pruned: {key}")
    if added:
        print(f"added {len(added)} new cell(s)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
