#!/usr/bin/env python3
"""(Re)bless the StableHLO lowering goldens.

Writes `tests/goldens/lowerings.json`: one sha256 fingerprint of the
lowered StableHLO text per (GAR x {plain, diag, masked-quorum}) cell,
plus the (jax version, backend) coordinates the fingerprints are
comparable under. The lint tier's drift gate
(`python -m byzantinemomentum_tpu.analysis --check-lowerings`) fails on
any unexplained change — run THIS script only when a lowering change is
intentional and reviewed, and commit the diff with the change that
caused it.

Idempotent: blessing twice under one toolchain is byte-identical
(sorted keys, no timestamps).

Usage: python scripts/bless_lowerings.py [--out PATH] [--check]
"""

import argparse
import os
import pathlib
import sys

ROOT = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(ROOT))

# Deterministic fingerprints need the CPU backend (this environment's
# sitecustomize may force a TPU platform; the config update after import
# is what actually sticks — see tests/conftest.py)
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from byzantinemomentum_tpu.analysis import lowering  # noqa: E402


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--out", type=str, default=None,
                        help="goldens path (default "
                             "tests/goldens/lowerings.json)")
    parser.add_argument("--check", action="store_true",
                        help="only report drift against the existing "
                             "goldens; do not rewrite")
    args = parser.parse_args()
    path = pathlib.Path(args.out) if args.out else lowering.GOLDENS_PATH

    if args.check:
        report = lowering.check(path)
        print(report)
        return 0 if report["status"] in ("ok", "incomparable") else 1

    before = path.read_bytes() if path.is_file() else None
    out = lowering.bless(path)
    changed = before != out.read_bytes()
    cells = len(lowering.CELL_GARS) * len(lowering.VARIANTS)
    print(f"blessed {cells} cells -> {out}"
          + (" (changed)" if changed else " (unchanged)"))
    return 0


if __name__ == "__main__":
    sys.exit(main())
