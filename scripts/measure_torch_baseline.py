#!/usr/bin/env python3
"""Measure the PyTorch-CPU baseline for the headline benchmark.

The reference cannot run in this environment (it imports torchvision at
module load, which is not installed here), so the baseline is a faithful
torch-CPU reimplementation of its hot loop in the reference's own
implementation style (BASELINE.md: "PyTorch-CPU steps/sec of attack.py"):

* sequential per-worker backprops on one shared model
  (reference `attack.py:786-795`),
* per-gradient L2 clip (`attack.py:791-794`),
* momentum at update (`attack.py:836-838`),
* empire attack, fixed factor (`attacks/identical.py:129-134`),
* Bulyan with reference-style per-pair distance tensor ops
  (`aggregators/bulyan.py:47-84`),
* the study-metric passes (`attack.py:842-866`).

Config: CIFAR-10 empire-cnn, n=25, f=5, batch 50, momentum 0.99, clip 5,
nb-for-study=1 — the reference grid's own Bulyan cell (Bulyan requires
n >= 4f+3, so the grid excludes it at f=11; reference `reproduce.py:165-209`,
`aggregators/bulyan.py:102-117`). With nb-for-study=1 the reference computes
max(nb_honests, 1) = n - f_real = 20 gradients per step (`attack.py:764`),
which is what the loop below does. Writes `BASELINE_MEASURED.json` at the
repo root, which `bench.py` uses as the `vs_baseline` denominator.

Usage: python scripts/measure_torch_baseline.py [--steps 20]
"""

import argparse
import json
import math
import pathlib
import sys
import time

import numpy as np
import torch
import torch.nn as nn
import torch.nn.functional as F

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from byzantinemomentum_tpu.data import sources  # noqa: E402

N_WORKERS = 25
F_DECL = 5
F_REAL = 5
N_HONEST = N_WORKERS - F_REAL
BATCH = 50
MOMENTUM = 0.99
CLIP = 5.0
LR = 0.01


class EmpireCnn(nn.Module):
    """Torch twin of `empire-cnn` (reference `experiments/models/empire.py:24-98`)."""

    def __init__(self):
        super().__init__()
        self.c1 = nn.Conv2d(3, 64, 3, padding=1)
        self.b1 = nn.BatchNorm2d(64)
        self.c2 = nn.Conv2d(64, 64, 3, padding=1)
        self.b2 = nn.BatchNorm2d(64)
        self.c3 = nn.Conv2d(64, 128, 3, padding=1)
        self.b3 = nn.BatchNorm2d(128)
        self.c4 = nn.Conv2d(128, 128, 3, padding=1)
        self.b4 = nn.BatchNorm2d(128)
        self.f1 = nn.Linear(8192, 128)
        self.f2 = nn.Linear(128, 10)

    def forward(self, x):
        x = self.b1(F.relu(self.c1(x)))
        x = self.b2(F.relu(self.c2(x)))
        x = F.dropout(F.max_pool2d(x, 2), 0.25, self.training)
        x = self.b3(F.relu(self.c3(x)))
        x = self.b4(F.relu(self.c4(x)))
        x = F.dropout(F.max_pool2d(x, 2), 0.25, self.training)
        x = x.flatten(1)
        x = F.dropout(F.relu(self.f1(x)), 0.25, self.training)
        return F.log_softmax(self.f2(x), dim=1)


def flat_grad(model):
    return torch.cat([p.grad.flatten() for p in model.parameters()])


def bulyan(stack, f):
    """Reference-style Bulyan: per-pair distance tensor ops + iterative
    Multi-Krum selection + averaged median (reference `bulyan.py:47-84`)."""
    n = stack.shape[0]
    dist = torch.full((n, n), math.inf)
    for i in range(n - 1):
        for j in range(i + 1, n):
            d = stack[i].sub(stack[j]).norm()
            dist[i, j] = dist[j, i] = d if torch.isfinite(d) else math.inf
    m_max = n - f - 2
    scores = []
    for i in range(n):
        row = sorted(dist[i, j].item() for j in range(n))
        scores.append(sum(row[:m_max]))
    rounds = n - 2 * f - 2
    selected = torch.empty((rounds, stack.shape[1]))
    for i in range(rounds):
        m_i = min(m_max, m_max - i)
        order = sorted(range(n), key=lambda g: scores[g])
        selected[i] = stack[order[:m_i]].mean(dim=0)
        scores[order[0]] = math.inf
    m2 = rounds - 2 * f
    med = selected.sort(dim=0).values[(rounds - 1) // 2]
    dev = (selected - med).abs()
    idx = dev.argsort(dim=0, stable=True)[:m2]
    return selected.gather(0, idx).mean(dim=0)


def avg_dev_max(samples):
    grad_avg = samples.mean(dim=0)
    norm_avg = grad_avg.norm().item()
    norm_max = grad_avg.abs().max().item()
    norm_var = sum(float((g - grad_avg).dot(g - grad_avg)) for g in samples)
    norm_dev = math.sqrt(norm_var / max(len(samples) - 1, 1))
    return grad_avg, norm_avg, norm_dev, norm_max


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--steps", type=int, default=20,
                        help="steps per measurement window")
    parser.add_argument("--windows", type=int, default=3,
                        help="number of measurement windows; the recorded "
                             "rate is the mean across windows and the "
                             "per-window rates carry the spread")
    args = parser.parse_args()

    assert N_WORKERS >= 4 * F_DECL + 3, (
        f"Bulyan requires n >= 4f+3 (n={N_WORKERS}, f={F_DECL})")

    torch.manual_seed(0)
    raw = sources.load_cifar(10)
    train_x = raw["train_x"].astype(np.float32) / 255.0
    mean = np.asarray([0.4914, 0.4822, 0.4465], np.float32)
    std = np.asarray([0.2023, 0.1994, 0.2010], np.float32)
    train_x = ((train_x - mean) / std).transpose(0, 3, 1, 2)  # NCHW
    train_y = raw["train_y"]

    model = EmpireCnn()
    model.train()
    loss_fn = nn.NLLLoss()
    rng = np.random.default_rng(0)
    momentum_buf = None

    def one_step():
        nonlocal momentum_buf
        grads = []
        losses = []
        for _ in range(N_HONEST):
            sel = rng.integers(0, len(train_x), BATCH)
            x = torch.from_numpy(train_x[sel])
            y = torch.from_numpy(train_y[sel]).long()
            model.zero_grad()
            loss = loss_fn(model(x), y)
            loss.backward()
            g = flat_grad(model)
            norm = g.norm().item()
            if norm > CLIP:
                g = g * (CLIP / norm)
            grads.append(g.detach().clone())
            losses.append(loss.item())
        honests = grads[:N_HONEST]
        avg = torch.stack(honests).mean(dim=0)
        byz = avg + 1.1 * (-avg)  # empire, factor 1.1
        stack = torch.stack(honests + [byz] * F_REAL)
        agg = bulyan(stack, F_DECL)
        assert torch.isfinite(agg).all(), "non-finite defense gradient"
        momentum_buf = (agg if momentum_buf is None
                        else MOMENTUM * momentum_buf + agg)
        with torch.no_grad():
            offset = 0
            for p in model.parameters():
                num = p.numel()
                p -= LR * momentum_buf[offset:offset + num].view_as(p)
                offset += num
        # Study metric passes (reference `attack.py:842-866`)
        sampled = torch.stack(grads)
        for part in (sampled, torch.stack(honests), stack[len(honests):]):
            avg_dev_max(part)
        agg.norm().item(), agg.abs().max().item()

    one_step()  # warmup (allocator, thread pools)
    window_rates = []
    elapsed_total = 0.0
    for _ in range(args.windows):
        start = time.monotonic()
        for _ in range(args.steps):
            one_step()
        elapsed = time.monotonic() - start
        elapsed_total += elapsed
        window_rates.append(args.steps / elapsed)
    steps_per_sec = float(np.mean(window_rates))
    spread = float(np.std(window_rates, ddof=1)) if args.windows > 1 else 0.0

    out = {
        "metric": "sim_steps_per_sec",
        "config": "CIFAR-10 empire-cnn, n=25 f=5, bulyan vs empire(1.1), "
                  "batch 50, momentum 0.99 at update, clip 5, "
                  "nb-for-study=1 (20 backprops/step), torch-CPU "
                  "reference-style loop",
        "torch_cpu_steps_per_sec": steps_per_sec,
        "window_steps_per_sec": window_rates,
        "window_spread_std": spread,
        "elapsed_s": elapsed_total,
        "steps": args.steps * args.windows,
        "windows": args.windows,
    }
    path = pathlib.Path(__file__).resolve().parent.parent / "BASELINE_MEASURED.json"
    path.write_text(json.dumps(out, indent=2) + "\n")
    print(json.dumps(out))


if __name__ == "__main__":
    main()
