#!/usr/bin/env python3
"""Diff two benchmark artifacts (`BENCH_r*.json`) cell by cell.

Each artifact is the harness wrapper around one `bench.py` run:
`{"n": round, "rc": exit code, "parsed": <bench.py's JSON line or null>}`
where the parsed payload carries the headline metric (`metric`/`value`)
and a `cells` dict of named sub-benchmarks with `steps_per_sec_*` fields.
Raw `bench.py` output JSON (the payload without the wrapper) is accepted
too.

Usage:
  python scripts/bench_compare.py [OLD.json NEW.json] [--tolerance 0.05]

With no files, the two newest `BENCH_r*.json` at the repo root are
compared (latest vs previous). Prints the per-cell steps/s deltas and
exits non-zero when any comparable cell regressed by more than
`--tolerance` (fractional: 0.05 = 5%).

Incomparability beats false alarms: a run that crashed (`rc != 0` /
`parsed: null`) or fell back to the CPU backend (`"backend":
"cpu-fallback"` — a down TPU tunnel, not a code regression; see
`bench.py:_ensure_backend`) makes the pair INCOMPARABLE — reported as
such, exit 0 — rather than counted as a regression.
"""

import argparse
import json
import pathlib
import re
import sys

ROOT = pathlib.Path(__file__).resolve().parent.parent

__all__ = ["load_artifact", "compare", "main"]

# Fields (headline + per-cell) holding a steps/s figure worth diffing
_RATE_KEY = re.compile(r"^(value|steps_per_sec(_\w+)?)$")


def load_artifact(path):
    """Parse one artifact into `(payload | None, reason | None)`:
    payload is bench.py's JSON (the wrapper's `parsed`, or the raw dict),
    None with a human-readable reason when the run is incomparable."""
    path = pathlib.Path(path)
    data = json.loads(path.read_text())
    if "parsed" in data or "rc" in data:  # the BENCH_r*.json wrapper
        if data.get("rc", 0) != 0 or not data.get("parsed"):
            return None, f"{path.name}: benchmark run failed " \
                         f"(rc={data.get('rc')}, no parsed payload)"
        payload = data["parsed"]
    else:
        payload = data
    if payload.get("backend") == "cpu-fallback":
        return None, (f"{path.name}: ran on the CPU fallback backend (down "
                      f"TPU tunnel) — steps/s not comparable to TPU runs")
    return payload, None


def _rates(payload):
    """Flatten one payload into `{cell.field: steps_per_sec}`."""
    rates = {}
    for key, value in payload.items():
        if _RATE_KEY.match(key) and isinstance(value, (int, float)):
            name = payload.get("metric", "headline") if key == "value" else key
            rates[name] = float(value)
    for cell, fields in (payload.get("cells") or {}).items():
        if not isinstance(fields, dict):
            continue
        for key, value in fields.items():
            if _RATE_KEY.match(key) and isinstance(value, (int, float)):
                rates[f"{cell}.{key}"] = float(value)
    return rates


def compare(old_payload, new_payload, tolerance):
    """`(rows, regressions)`: per-cell `(name, old, new, delta_frac)` for
    every steps/s field present in BOTH payloads, and the subset whose
    delta is below `-tolerance`."""
    old_rates = _rates(old_payload)
    new_rates = _rates(new_payload)
    rows = []
    for name in sorted(old_rates):
        if name not in new_rates or old_rates[name] <= 0:
            continue
        old, new = old_rates[name], new_rates[name]
        rows.append((name, old, new, new / old - 1.0))
    regressions = [r for r in rows if r[3] < -tolerance]
    return rows, regressions


def _latest_pair():
    found = sorted(ROOT.glob("BENCH_r*.json"))
    if len(found) < 2:
        return None
    return found[-2], found[-1]


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="bench_compare",
        description="Diff two BENCH_r*.json artifacts, printing per-cell "
                    "steps/s deltas; exits 1 past --tolerance regression")
    parser.add_argument("files", nargs="*",
                        help="OLD.json NEW.json (default: the two newest "
                             "BENCH_r*.json at the repo root)")
    parser.add_argument("--tolerance", type=float, default=0.05,
                        help="fractional regression threshold (default "
                             "0.05 = 5%%)")
    args = parser.parse_args(argv)
    if args.tolerance < 0:
        parser.error(f"negative tolerance {args.tolerance}")

    if len(args.files) == 2:
        old_path, new_path = args.files
    elif not args.files:
        pair = _latest_pair()
        if pair is None:
            print("bench_compare: fewer than two BENCH_r*.json artifacts; "
                  "nothing to compare")
            return 0
        old_path, new_path = pair
    else:
        parser.error("expected exactly two files (or none for latest pair)")

    payloads = []
    for path in (old_path, new_path):
        try:
            payload, reason = load_artifact(path)
        except (OSError, json.JSONDecodeError) as err:
            print(f"bench_compare: cannot read {path}: {err}")
            return 2
        if payload is None:
            print(f"bench_compare: INCOMPARABLE — {reason}")
            return 0
        payloads.append(payload)

    old_payload, new_payload = payloads
    rows, regressions = compare(old_payload, new_payload, args.tolerance)
    print(f"bench_compare: {pathlib.Path(old_path).name} -> "
          f"{pathlib.Path(new_path).name} "
          f"(tolerance {args.tolerance * 100:.1f}%)")
    if not rows:
        print("  no common steps/s cells; nothing to compare")
        return 0
    width = max(len(name) for name, *_ in rows)
    for name, old, new, delta in rows:
        flag = "  REGRESSED" if delta < -args.tolerance else ""
        print(f"  {name:<{width}}  {old:10.3f} -> {new:10.3f} steps/s  "
              f"{delta * 100:+7.2f}%{flag}")
    if regressions:
        print(f"bench_compare: {len(regressions)} cell(s) regressed past "
              f"the {args.tolerance * 100:.1f}% tolerance")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
