#!/usr/bin/env python3
"""Diff two benchmark artifacts (`BENCH_r*.json`) cell by cell — or two
phase-attribution artifacts (`attribution.json`, obs/attrib) budget by
budget.

Each bench artifact is the harness wrapper around one `bench.py` run:
`{"n": round, "rc": exit code, "parsed": <bench.py's JSON line or null>}`
where the parsed payload carries the headline metric (`metric`/`value`)
and a `cells` dict of named sub-benchmarks with `steps_per_sec_*` fields.
Raw `bench.py` output JSON (the payload without the wrapper) is accepted
too, as is an `attribution.json` (`"kind": "attribution"`): for those the
diff runs over per-phase ms/step, the relayout-copy budget and the
host-gap fraction — COST metrics, so the gate fails on *growth* past the
tolerance. An `ATTRIB_serve*.json` pair (`"kind": "serve_attribution"`,
`scripts/serve_loadgen.py --trace`) gets the same treatment per SERVE
phase (queue wait, pack, dispatch, resolver wake-up, device, resolve:
p50/p99 growth past tolerance over an absolute noise floor fails; the
tracing-overhead row is informational). A `BENCH_serve.json` pair
(`"kind": "serve"`, `scripts/serve_loadgen.py`) gates the aggregation
service the same way:
p50/p99 latencies are costs (growth fails), aggregations/s and the
batched-vs-sequential speedup are rates (drops fail), and cross-backend
pairs are INCOMPARABLE. A `BENCH_serve_fleet.json` pair (`"kind":
"serve_fleet"`, `--fleet`) compares aggregations/s per (scenario,
shard-count) cell and fails on any recovery invariant flipping false;
pairs from different fleet sizes, host core counts or isolation modes
are INCOMPARABLE — a 4-shard rate says nothing about a 2-shard one. An
`ATTRIB_serve_fleet*.json` pair (`"kind": "serve_fleet_attribution"`,
`--fleet --trace`) gates the JOINED per-hop columns the same cost-wise
way: route, wire residual, shard queue wait, pack, dispatch, device,
resolve p50/p99 per (scenario, shard count) — growth past tolerance
over the same absolute floor fails BY HOP NAME, so a convoy that moves
from the device into the shard queue cannot hide inside an unchanged
end-to-end p99; tiling error, the join overhead fraction and the zipf
queue-skew are informational. Mixed-kind, cross-backend, cross-core and
different-shard-count-set pairs are INCOMPARABLE. A
`BENCH_metrics*.json` pair (`"kind": "metrics_overhead"`,
`--metrics-overhead`) gates the metrics-plane registry cost: the
paired on/off agg/s are rates, the overhead fraction is a cost, and
the 2% `within_bound` acceptance bit flipping false fails regardless
of tolerance; `--smoke` metrics artifacts are INCOMPARABLE. That is the phase-budget gate: a PR that regrows the relayout
copies or host gaps the r5 packing work removed (PERF_NOTES.md) fails CI
here instead of silently eating the win inside an unchanged steps/s
tolerance band.

Usage:
  python scripts/bench_compare.py [OLD.json NEW.json] [--tolerance 0.05]

With no files, the two newest `BENCH_r*.json` at the repo root are
compared (latest vs previous). Exits non-zero when any comparable cell
regressed by more than `--tolerance` (fractional: 0.05 = 5%).

Incomparability beats false alarms: a run that crashed (`rc != 0` /
`parsed: null`), fell back to the CPU backend (`"backend":
"cpu-fallback"` — a down TPU tunnel, not a code regression; see
`bench.py:_ensure_backend`), or a pair mixing artifact kinds or
attribution backends makes the pair INCOMPARABLE — reported as such,
exit 0 — rather than counted as a regression.
"""

import argparse
import json
import pathlib
import re
import sys

ROOT = pathlib.Path(__file__).resolve().parent.parent

__all__ = ["load_artifact", "compare", "compare_attribution",
           "compare_cluster", "compare_health", "compare_metrics",
           "compare_serve", "compare_serve_attribution",
           "compare_serve_fleet", "compare_serve_fleet_attribution",
           "main"]

# Fields (headline + per-cell) holding a steps/s figure worth diffing
_RATE_KEY = re.compile(r"^(value|steps_per_sec(_\w+)?)$")


def load_artifact(path):
    """Parse one artifact into `(payload | None, reason | None)`:
    payload is bench.py's JSON (the wrapper's `parsed`, or the raw dict),
    None with a human-readable reason when the run is incomparable."""
    path = pathlib.Path(path)
    data = json.loads(path.read_text())
    if "parsed" in data or "rc" in data:  # the BENCH_r*.json wrapper
        if data.get("rc", 0) != 0 or not data.get("parsed"):
            return None, f"{path.name}: benchmark run failed " \
                         f"(rc={data.get('rc')}, no parsed payload)"
        payload = data["parsed"]
    else:
        payload = data
    if payload.get("backend") == "cpu-fallback":
        return None, (f"{path.name}: ran on the CPU fallback backend (down "
                      f"TPU tunnel) — steps/s not comparable to TPU runs")
    return payload, None


def _rates(payload):
    """Flatten one payload into `{cell.field: steps_per_sec}`."""
    rates = {}
    for key, value in payload.items():
        if _RATE_KEY.match(key) and isinstance(value, (int, float)):
            name = payload.get("metric", "headline") if key == "value" else key
            rates[name] = float(value)
    for cell, fields in (payload.get("cells") or {}).items():
        if not isinstance(fields, dict):
            continue
        for key, value in fields.items():
            if _RATE_KEY.match(key) and isinstance(value, (int, float)):
                rates[f"{cell}.{key}"] = float(value)
    return rates


def compare(old_payload, new_payload, tolerance):
    """`(rows, regressions)`: per-cell `(name, old, new, delta_frac)` for
    every steps/s field present in BOTH payloads, and the subset whose
    delta is below `-tolerance`."""
    old_rates = _rates(old_payload)
    new_rates = _rates(new_payload)
    rows = []
    for name in sorted(old_rates):
        if name not in new_rates or old_rates[name] <= 0:
            continue
        old, new = old_rates[name], new_rates[name]
        rows.append((name, old, new, new / old - 1.0))
    regressions = [r for r in rows if r[3] < -tolerance]
    return rows, regressions


# Cost cells below this many ms/step are measurement noise on any backend;
# the phase gate never fails on their relative growth alone
_BUDGET_FLOOR_MS = 0.05


def _budgets(attribution):
    """Flatten one attribution payload into `{name: cost}` — per-phase
    ms/step, the op-class budgets (the relayout budget is THE regression
    the r5 packing win must not silently lose), and the host-gap
    fraction."""
    budgets = {}
    for name, entry in (attribution.get("phases") or {}).items():
        if isinstance(entry, dict) and isinstance(
                entry.get("ms"), (int, float)):
            budgets[f"phase.{name}.ms"] = float(entry["ms"])
    for name, value in (attribution.get("op_classes") or {}).items():
        if isinstance(value, (int, float)):
            budgets[f"class.{name}.ms"] = float(value)
    for key in ("total_ms", "host_gap_fraction"):
        value = attribution.get(key)
        if isinstance(value, (int, float)):
            budgets[key] = float(value)
    return budgets


def compare_attribution(old_payload, new_payload, tolerance):
    """The phase-budget gate: `(rows, regressions)` over cost budgets
    present in BOTH attribution artifacts. Costs regress by GROWING, so a
    regression is `new > old * (1 + tolerance)` — with an absolute floor
    (`_BUDGET_FLOOR_MS`, or 0.01 for the dimensionless host-gap fraction)
    so sub-noise budgets cannot flake the gate."""
    old_budgets = _budgets(old_payload)
    new_budgets = _budgets(new_payload)
    rows = []
    regressions = []
    for name in sorted(old_budgets):
        if name not in new_budgets:
            continue
        old, new = old_budgets[name], new_budgets[name]
        delta = (new / old - 1.0) if old > 0 else (0.0 if new <= 0
                                                   else float("inf"))
        rows.append((name, old, new, delta))
        floor = 0.01 if name == "host_gap_fraction" else _BUDGET_FLOOR_MS
        if new > old * (1.0 + tolerance) and new - old > floor:
            regressions.append((name, old, new, delta))
    return rows, regressions


# Serve latency cells below this many ms are scheduler noise on any
# host; the gate never fails on their relative growth alone
_SERVE_FLOOR_MS = 0.5


def _serve_metrics(payload):
    """Flatten a serve artifact (`scripts/serve_loadgen.py`) into
    `{(name, is_cost): value}`: per-cell p50/p99 latencies are COSTS
    (growth regresses), aggregations/s are RATES (drop regresses), and
    the heterogeneous workload's compile counts are COSTS with no noise
    floor (a compiled-program count that grows means shapes fell off the
    bucket ladder — the exact regression the r10 d-bucketing removed)."""
    metrics = {}
    for cell, fields in (payload.get("cells") or {}).items():
        if not isinstance(fields, dict):
            continue
        for key, cost in (("p50_ms", True), ("p99_ms", True),
                          ("agg_per_sec", False)):
            value = fields.get(key)
            if isinstance(value, (int, float)):
                metrics[(f"{cell}.{key}", cost)] = float(value)
    value = payload.get("speedup_batched_vs_sequential")
    if isinstance(value, (int, float)):
        metrics[("speedup_batched_vs_sequential", False)] = float(value)
    for key in ("distinct_cells", "distinct_programs", "warm_compiles"):
        value = (payload.get("compiles") or {}).get(key)
        if isinstance(value, (int, float)):
            metrics[(f"compiles.{key}", True)] = float(value)
    return metrics


def compare_serve(old_payload, new_payload, tolerance):
    """The serve-latency gate: `(rows, regressions)` over metrics present
    in BOTH artifacts. Latency costs regress by GROWING past tolerance
    (with the `_SERVE_FLOOR_MS` absolute floor, as the phase-budget
    gate), throughput rates by DROPPING past it, and `compiles.*` counts
    regress on ANY growth (they are exact integers — no noise floor, no
    tolerance: one extra compiled program is a ladder hole)."""
    old_metrics = _serve_metrics(old_payload)
    new_metrics = _serve_metrics(new_payload)
    # The speedup is a RATIO of two metrics gated on their own (batched
    # rate: drop fails; sequential rate: a FASTER baseline can never be a
    # regression). A ratio drop explained entirely by a faster sequential
    # baseline is therefore not a serving regression — only flag the
    # speedup when the batched capacity itself also dropped, so the ratio
    # adds signal instead of double-counting a baseline improvement.
    batched_key = ("serve.batched.agg_per_sec", False)
    batched_dropped = (
        batched_key in old_metrics and batched_key in new_metrics
        and new_metrics[batched_key] < old_metrics[batched_key])
    rows = []
    regressions = []
    for (name, cost) in sorted(old_metrics, key=lambda k: k[0]):
        if (name, cost) not in new_metrics:
            continue
        old, new = old_metrics[(name, cost)], new_metrics[(name, cost)]
        delta = (new / old - 1.0) if old > 0 else (0.0 if new <= 0
                                                   else float("inf"))
        rows.append((name, old, new, delta))
        if name.startswith("compiles."):
            if new > old:
                regressions.append((name, old, new, delta))
        elif cost:
            if (new > old * (1.0 + tolerance)
                    and new - old > _SERVE_FLOOR_MS):
                regressions.append((name, old, new, delta))
        elif delta < -tolerance:
            if (name == "speedup_batched_vs_sequential"
                    and not batched_dropped):
                continue  # baseline-driven ratio drop (see note above)
            regressions.append((name, old, new, delta))
    return rows, regressions


# Serve-attribution phases below this many ms are scheduler noise on a
# 1-core host (a p99 of 0.1 ms doubles on a context switch); the gate
# never fails on their relative growth alone
_SERVE_ATTRIB_FLOOR_MS = 0.25


def compare_serve_attribution(old_payload, new_payload, tolerance):
    """The serve-attribution gate over two `ATTRIB_serve*.json` payloads
    (`scripts/serve_loadgen.py --trace`): per-phase p50/p99 ms and the
    end-to-end latency percentiles are COSTS — the gate fails on GROWTH
    past `tolerance` with the `_SERVE_ATTRIB_FLOOR_MS` absolute floor
    (the training phase-budget discipline, applied per serve phase so a
    regression in resolver wake-up or host-side packing fails CI by
    name instead of hiding inside an unchanged aggregate p99). The
    tracing-overhead fraction and the queue-depth/occupancy rows are
    INFORMATIONAL (they follow load, not code quality). Mixed-kind and
    cross-backend pairs are the caller's INCOMPARABLE case."""
    def costs(payload):
        out = {}
        for phase, cell in (payload.get("phases") or {}).items():
            if not isinstance(cell, dict):
                continue
            for key in ("p50_ms", "p99_ms"):
                value = cell.get(key)
                if isinstance(value, (int, float)):
                    out[f"phase.{phase}.{key}"] = float(value)
        for key in ("p50_ms", "p99_ms"):
            value = (payload.get("latency") or {}).get(key)
            if isinstance(value, (int, float)):
                out[f"latency.{key}"] = float(value)
        return out

    old_costs, new_costs = costs(old_payload), costs(new_payload)
    rows = []
    regressions = []
    for name in sorted(old_costs):
        if name not in new_costs:
            continue
        old, new = old_costs[name], new_costs[name]
        delta = (new / old - 1.0) if old > 0 else (0.0 if new <= 0
                                                   else float("inf"))
        rows.append((name, old, new, delta))
        if (new > old * (1.0 + tolerance)
                and new - old > _SERVE_ATTRIB_FLOOR_MS):
            regressions.append((name, old, new, delta))
    for key in ("frac",):
        old = (old_payload.get("overhead") or {}).get(key)
        new = (new_payload.get("overhead") or {}).get(key)
        if isinstance(old, (int, float)) and isinstance(new, (int, float)):
            delta = (new / old - 1.0) if old > 0 else (0.0 if new <= 0
                                                      else float("inf"))
            rows.append((f"overhead.{key} (info)", float(old), float(new),
                         delta))
    return rows, regressions


def compare_serve_fleet(old_payload, new_payload, tolerance):
    """The sharded-fleet gate over two `BENCH_serve_fleet.json` payloads
    (`scripts/serve_loadgen.py --fleet`): aggregations/s per (scenario,
    shard-count) cell is a RATE — the gate fails on a DROP past
    `tolerance` — and only cells present in BOTH artifacts at the SAME
    shard count are compared (a 2-shard rate vs a 4-shard rate measures
    fleet size, not code; the caller treats mismatched shard-count sets
    as INCOMPARABLE before reaching here). The recovery booleans
    (parked-line recovery, survivor monotonicity, the re-warm bound)
    regress by FLIPPING false — any of them false in the new artifact
    fails regardless of tolerance, because a fleet that corrupts a
    survivor's verdict stream during failover is wrong at any speed.
    `fleet_speedup` is INFORMATIONAL: on a 1-core host (`host_cores`) a
    shard count cannot buy parallelism, so its trajectory is rendered by
    bench_history, not gated."""
    rows = []
    regressions = []
    old_scen = old_payload.get("scenarios") or {}
    new_scen = new_payload.get("scenarios") or {}
    for scenario in sorted(old_scen):
        if scenario not in new_scen:
            continue
        for count in sorted(old_scen[scenario],
                            key=lambda c: (len(c), c)):
            if count not in new_scen[scenario]:
                continue
            old = (old_scen[scenario][count] or {}).get("agg_per_sec")
            new = (new_scen[scenario][count] or {}).get("agg_per_sec")
            if not (isinstance(old, (int, float)) and old > 0
                    and isinstance(new, (int, float))):
                continue
            delta = new / old - 1.0
            rows.append((f"{scenario}.shards_{count}.agg_per_sec",
                         float(old), float(new), delta))
            if delta < -tolerance:
                regressions.append(rows[-1])
    for key in ("parked_line_recovered", "survivor_monotonic",
                "rewarm_no_faster_than_fresh"):
        old = (old_payload.get("recovery") or {}).get(key)
        new = (new_payload.get("recovery") or {}).get(key)
        if isinstance(old, bool) and isinstance(new, bool):
            rows.append((f"recovery.{key}", float(old), float(new),
                         0.0 if new >= old else -1.0))
            if not new:
                regressions.append(rows[-1])
    old = old_payload.get("fleet_speedup")
    new = new_payload.get("fleet_speedup")
    if isinstance(old, (int, float)) and isinstance(new, (int, float)):
        delta = (new / old - 1.0) if old > 0 else 0.0
        rows.append(("fleet_speedup (info)", float(old), float(new),
                     delta))
    return rows, regressions


def compare_serve_fleet_attribution(old_payload, new_payload, tolerance):
    """The fleet-attribution gate over two `ATTRIB_serve_fleet*.json`
    payloads (`scripts/serve_loadgen.py --fleet --trace`): every JOINED
    per-hop column — route, wire residual, shard queue wait, pack,
    dispatch, device, resolve — is a COST per (scenario, shard count),
    so the gate fails on p50/p99 GROWTH past `tolerance` over the
    `_SERVE_ATTRIB_FLOOR_MS` absolute floor, named down to the hop
    (`zipf.shards_4.hop.shard_queue.p99_ms`). That is the whole point
    of the join: a convoy migrating from the device into a shard's
    admission queue FAILS here by name instead of washing out inside a
    stable end-to-end p99. Per-cell tiling error, the paired join
    overhead fraction and the zipf queue-wait skew are INFORMATIONAL
    (skew follows key popularity, not code). The caller treats
    mixed-kind, cross-backend, cross-core and mismatched shard-count
    sets as INCOMPARABLE before reaching here."""
    def costs(payload):
        out = {}
        for scenario, counts in sorted(
                (payload.get("scenarios") or {}).items()):
            if not isinstance(counts, dict):
                continue
            for count, row in sorted(counts.items(),
                                     key=lambda kv: (len(kv[0]), kv[0])):
                for hop, cell in sorted(((row or {}).get("hops")
                                         or {}).items()):
                    if not isinstance(cell, dict):
                        continue
                    for key in ("p50_ms", "p99_ms"):
                        value = cell.get(key)
                        if isinstance(value, (int, float)):
                            out[f"{scenario}.shards_{count}.hop."
                                f"{hop}.{key}"] = float(value)
        return out

    old_costs, new_costs = costs(old_payload), costs(new_payload)
    rows = []
    regressions = []
    for name in sorted(old_costs):
        if name not in new_costs:
            continue
        old, new = old_costs[name], new_costs[name]
        delta = (new / old - 1.0) if old > 0 else (0.0 if new <= 0
                                                   else float("inf"))
        rows.append((name, old, new, delta))
        if (new > old * (1.0 + tolerance)
                and new - old > _SERVE_ATTRIB_FLOOR_MS):
            regressions.append((name, old, new, delta))
    for label, old, new in (
            ("overhead.frac",
             (old_payload.get("overhead") or {}).get("frac"),
             (new_payload.get("overhead") or {}).get("frac")),
            ("zipf_queue_skew.max_over_min",
             (old_payload.get("zipf_queue_skew") or {}).get("max_over_min"),
             (new_payload.get("zipf_queue_skew") or {}).get("max_over_min"))):
        if isinstance(old, (int, float)) and isinstance(new, (int, float)):
            delta = (new / old - 1.0) if old > 0 else (0.0 if new <= 0
                                                       else float("inf"))
            rows.append((f"{label} (info)", float(old), float(new), delta))
    return rows, regressions


# The health-overhead fraction is an absolute few-percent figure; growth
# below one percentage point is measurement noise on any host and never
# fails the gate on its own
_HEALTH_OVERHEAD_FLOOR = 0.01


def compare_health(old_payload, new_payload, tolerance):
    """The flight-recorder overhead gate over two `BENCH_health*.json`
    artifacts (`scripts/health_overhead.py`): the paired on/off steps/s
    rates regress by DROPPING past tolerance, and the overhead fraction
    — the telemetry discipline's headline number — regresses by GROWING
    past tolerance over a one-point absolute floor
    (`_HEALTH_OVERHEAD_FLOOR`). Cross-backend pairs and `--smoke`
    artifacts (3-pair CI form — harness proof, not a measurement) are
    the caller's INCOMPARABLE case."""
    rows = []
    regressions = []
    for key in ("steps_per_sec_off", "steps_per_sec_on"):
        old, new = old_payload.get(key), new_payload.get(key)
        if not (isinstance(old, (int, float)) and old > 0
                and isinstance(new, (int, float))):
            continue
        delta = new / old - 1.0
        rows.append((key, float(old), float(new), delta))
        if delta < -tolerance:
            regressions.append(rows[-1])
    old = old_payload.get("overhead_frac")
    new = new_payload.get("overhead_frac")
    if isinstance(old, (int, float)) and isinstance(new, (int, float)):
        delta = (new / old - 1.0) if old > 0 else (0.0 if new <= old
                                                   else float("inf"))
        rows.append(("overhead_frac", float(old), float(new), delta))
        if (new > old * (1.0 + tolerance)
                and new - old > _HEALTH_OVERHEAD_FLOOR):
            regressions.append(rows[-1])
    return rows, regressions


# The metrics-plane overhead is bounded at 2% by construction (the r18
# acceptance bound); growth below half a percentage point absolute is
# window noise and never fails the gate on its own
_METRICS_OVERHEAD_FLOOR = 0.005


def compare_metrics(old_payload, new_payload, tolerance):
    """The metrics-plane overhead gate over two `BENCH_metrics*.json`
    artifacts (`scripts/serve_loadgen.py --metrics-overhead`): the
    paired registry-on/registry-off agg/s rates regress by DROPPING
    past tolerance, the overhead fraction regresses by GROWING past
    tolerance over an absolute floor (`_METRICS_OVERHEAD_FLOOR`), and
    `within_bound` flipping false — the 2% acceptance bit itself — is
    a regression regardless of tolerance. Cross-backend pairs and
    `--smoke` artifacts are the caller's INCOMPARABLE case."""
    rows = []
    regressions = []
    for key in ("agg_per_sec_metrics_off", "agg_per_sec_metrics_on"):
        old, new = old_payload.get(key), new_payload.get(key)
        if not (isinstance(old, (int, float)) and old > 0
                and isinstance(new, (int, float))):
            continue
        delta = new / old - 1.0
        rows.append((key, float(old), float(new), delta))
        if delta < -tolerance:
            regressions.append(rows[-1])
    old = old_payload.get("overhead_frac")
    new = new_payload.get("overhead_frac")
    if isinstance(old, (int, float)) and isinstance(new, (int, float)):
        delta = (new / old - 1.0) if old > 0 else (0.0 if new <= old
                                                   else float("inf"))
        rows.append(("overhead_frac", float(old), float(new), delta))
        if (new > old * (1.0 + tolerance)
                and new - old > _METRICS_OVERHEAD_FLOOR):
            regressions.append(rows[-1])
    old = old_payload.get("within_bound")
    new = new_payload.get("within_bound")
    if isinstance(old, bool) and isinstance(new, bool):
        rows.append(("within_bound", float(old), float(new),
                     float(new) - float(old)))
        if old and not new:
            regressions.append(rows[-1])
    return rows, regressions


def compare_cluster(old_payload, new_payload, tolerance):
    """The multi-host gate over two `CLUSTER_r*.json` artifacts
    (`scripts/cluster_smoke.py`): cluster steps/s is a RATE (drop past
    tolerance fails); the recovery-step count and fleet attempts are
    INFORMATIONAL rows (they follow the fault plan's kill step, not code
    quality — bench_history renders their trajectory). Pairs from
    different backends or host counts are the caller's INCOMPARABLE
    case, as is any non-`ok` artifact (e.g. `unavailable`)."""
    rows = []
    regressions = []
    old_rate = old_payload.get("steps_per_sec")
    new_rate = new_payload.get("steps_per_sec")
    if (isinstance(old_rate, (int, float)) and old_rate > 0
            and isinstance(new_rate, (int, float))):
        delta = new_rate / old_rate - 1.0
        rows.append(("cluster.steps_per_sec", float(old_rate),
                     float(new_rate), delta))
        if delta < -tolerance:
            regressions.append(rows[-1])
    for key in ("recovery_steps", "events"):
        old = (old_payload.get("recovery") or {}).get(key)
        new = (new_payload.get("recovery") or {}).get(key)
        if isinstance(old, (int, float)) and isinstance(new, (int, float)):
            delta = (new / old - 1.0) if old > 0 else (0.0 if new <= 0
                                                      else float("inf"))
            rows.append((f"recovery.{key} (info)", float(old), float(new),
                         delta))
    # Elastic rounds (PR 17), both informational: re-executed steps after
    # the shrink, and how long the straggler policy held a SUSPECT before
    # killing (the realized bounded wait). The caller already refused
    # pairs whose shrink rounds survived at different fleet sizes.
    for block, key in (("shrink_round", "recovery_steps"),
                       ("straggler_round", "suspect_s")):
        old = (old_payload.get(block) or {}).get(key)
        new = (new_payload.get(block) or {}).get(key)
        if isinstance(old, (int, float)) and isinstance(new, (int, float)):
            delta = (new / old - 1.0) if old > 0 else (0.0 if new <= 0
                                                      else float("inf"))
            rows.append((f"{block}.{key} (info)", float(old), float(new),
                         delta))
    return rows, regressions


def _latest_pair():
    found = sorted(ROOT.glob("BENCH_r*.json"))
    if len(found) < 2:
        return None
    return found[-2], found[-1]


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="bench_compare",
        description="Diff two BENCH_r*.json artifacts, printing per-cell "
                    "steps/s deltas; exits 1 past --tolerance regression")
    parser.add_argument("files", nargs="*",
                        help="OLD.json NEW.json (default: the two newest "
                             "BENCH_r*.json at the repo root)")
    parser.add_argument("--tolerance", type=float, default=0.05,
                        help="fractional regression threshold (default "
                             "0.05 = 5%%)")
    args = parser.parse_args(argv)
    if args.tolerance < 0:
        parser.error(f"negative tolerance {args.tolerance}")

    if len(args.files) == 2:
        old_path, new_path = args.files
    elif not args.files:
        pair = _latest_pair()
        if pair is None:
            print("bench_compare: fewer than two BENCH_r*.json artifacts; "
                  "nothing to compare")
            return 0
        old_path, new_path = pair
    else:
        parser.error("expected exactly two files (or none for latest pair)")

    payloads = []
    for path in (old_path, new_path):
        try:
            payload, reason = load_artifact(path)
        except (OSError, json.JSONDecodeError) as err:
            print(f"bench_compare: cannot read {path}: {err}")
            return 2
        if payload is None:
            print(f"bench_compare: INCOMPARABLE — {reason}")
            return 0
        payloads.append(payload)

    old_payload, new_payload = payloads
    print(f"bench_compare: {pathlib.Path(old_path).name} -> "
          f"{pathlib.Path(new_path).name} "
          f"(tolerance {args.tolerance * 100:.1f}%)")

    is_fleet_attr = [p.get("kind") == "serve_fleet_attribution"
                     for p in payloads]
    if any(is_fleet_attr):
        # Fleet-attribution gate over two ATTRIB_serve_fleet*.json
        # artifacts: joined per-hop columns per (scenario, shard count)
        if not all(is_fleet_attr):
            print("bench_compare: INCOMPARABLE — one artifact is a fleet "
                  "attribution, the other is not")
            return 0
        backends = [p.get("backend") for p in payloads]
        if backends[0] != backends[1]:
            print(f"bench_compare: INCOMPARABLE — fleet attributions from "
                  f"different backends ({backends[0]} vs {backends[1]})")
            return 0
        cores = [p.get("host_cores") for p in payloads]
        if cores[0] != cores[1]:
            print(f"bench_compare: INCOMPARABLE — fleet attributions from "
                  f"hosts with different core counts ({cores[0]} vs "
                  f"{cores[1]}) — hop latency is core-bound")
            return 0
        sizes = [sorted((p.get("config") or {}).get("shard_counts") or [],
                        key=str) for p in payloads]
        if sizes[0] != sizes[1]:
            print(f"bench_compare: INCOMPARABLE — different fleet sizes "
                  f"({sizes[0]} vs {sizes[1]} shards)")
            return 0
        rows, regressions = compare_serve_fleet_attribution(
            old_payload, new_payload, args.tolerance)
        if not rows:
            print("  no common joined hop cells; nothing to compare")
            return 0
        flagged = {row[0] for row in regressions}
        width = max(len(name) for name, *_ in rows)
        for name, old, new, delta in rows:
            flag = "  REGRESSED" if name in flagged else ""
            print(f"  {name:<{width}}  {old:10.4f} -> {new:10.4f}  "
                  f"{delta * 100:+7.2f}%{flag}")
        if regressions:
            print(f"bench_compare: {len(regressions)} joined hop(s) grew "
                  f"past the {args.tolerance * 100:.1f}% tolerance")
            return 1
        return 0

    is_serve_attr = [p.get("kind") == "serve_attribution" for p in payloads]
    if any(is_serve_attr):
        # Serve-attribution gate over two ATTRIB_serve*.json artifacts
        if not all(is_serve_attr):
            print("bench_compare: INCOMPARABLE — one artifact is a serve "
                  "attribution, the other is not")
            return 0
        backends = [p.get("backend") for p in payloads]
        if backends[0] != backends[1]:
            print(f"bench_compare: INCOMPARABLE — serve attributions from "
                  f"different backends ({backends[0]} vs {backends[1]})")
            return 0
        rows, regressions = compare_serve_attribution(
            old_payload, new_payload, args.tolerance)
        if not rows:
            print("  no common serve phases; nothing to compare")
            return 0
        flagged = {row[0] for row in regressions}
        width = max(len(name) for name, *_ in rows)
        for name, old, new, delta in rows:
            flag = "  REGRESSED" if name in flagged else ""
            print(f"  {name:<{width}}  {old:10.4f} -> {new:10.4f}  "
                  f"{delta * 100:+7.2f}%{flag}")
        if regressions:
            print(f"bench_compare: {len(regressions)} serve phase(s) grew "
                  f"past the {args.tolerance * 100:.1f}% tolerance")
            return 1
        return 0

    is_fleet = [p.get("kind") == "serve_fleet" for p in payloads]
    if any(is_fleet):
        # Sharded-fleet gate over two BENCH_serve_fleet.json artifacts
        if not all(is_fleet):
            print("bench_compare: INCOMPARABLE — one artifact is a serve "
                  "fleet report, the other is not")
            return 0
        backends = [p.get("backend") for p in payloads]
        if backends[0] != backends[1]:
            print(f"bench_compare: INCOMPARABLE — fleet runs from "
                  f"different backends ({backends[0]} vs {backends[1]})")
            return 0
        cores = [p.get("host_cores") for p in payloads]
        if cores[0] != cores[1]:
            print(f"bench_compare: INCOMPARABLE — fleet runs from hosts "
                  f"with different core counts ({cores[0]} vs {cores[1]}) "
                  f"— shard throughput is core-bound")
            return 0
        isolation = [p.get("isolation") for p in payloads]
        if isolation[0] != isolation[1]:
            print(f"bench_compare: INCOMPARABLE — fleet isolation modes "
                  f"differ ({isolation[0]} vs {isolation[1]})")
            return 0
        sizes = [sorted((p.get("config") or {}).get("shard_counts") or [],
                        key=str) for p in payloads]
        if sizes[0] != sizes[1]:
            print(f"bench_compare: INCOMPARABLE — different fleet sizes "
                  f"({sizes[0]} vs {sizes[1]} shards)")
            return 0
        rows, regressions = compare_serve_fleet(old_payload, new_payload,
                                                args.tolerance)
        if not rows:
            print("  no common fleet cells; nothing to compare")
            return 0
        flagged = {row[0] for row in regressions}
        width = max(len(name) for name, *_ in rows)
        for name, old, new, delta in rows:
            flag = "  REGRESSED" if name in flagged else ""
            print(f"  {name:<{width}}  {old:10.3f} -> {new:10.3f}  "
                  f"{delta * 100:+7.2f}%{flag}")
        if regressions:
            print(f"bench_compare: {len(regressions)} fleet metric(s) "
                  f"regressed past the {args.tolerance * 100:.1f}% "
                  f"tolerance")
            return 1
        return 0

    is_serve = [p.get("kind") == "serve" for p in payloads]
    if any(is_serve):
        # Serve-latency gate over two BENCH_serve.json artifacts
        if not all(is_serve):
            print("bench_compare: INCOMPARABLE — one artifact is a serve "
                  "load report, the other is not")
            return 0
        backends = [p.get("backend") for p in payloads]
        if backends[0] != backends[1]:
            print(f"bench_compare: INCOMPARABLE — serve runs from "
                  f"different backends ({backends[0]} vs {backends[1]})")
            return 0
        rows, regressions = compare_serve(old_payload, new_payload,
                                          args.tolerance)
        if not rows:
            print("  no common serve metrics; nothing to compare")
            return 0
        flagged = {row[0] for row in regressions}
        width = max(len(name) for name, *_ in rows)
        for name, old, new, delta in rows:
            flag = "  REGRESSED" if name in flagged else ""
            print(f"  {name:<{width}}  {old:10.3f} -> {new:10.3f}  "
                  f"{delta * 100:+7.2f}%{flag}")
        if regressions:
            print(f"bench_compare: {len(regressions)} serve metric(s) "
                  f"regressed past the {args.tolerance * 100:.1f}% "
                  f"tolerance")
            return 1
        return 0

    is_health = [p.get("kind") == "health_overhead" for p in payloads]
    if any(is_health):
        # Flight-recorder overhead gate over two BENCH_health*.json
        if not all(is_health):
            print("bench_compare: INCOMPARABLE — one artifact is a "
                  "health-overhead report, the other is not")
            return 0
        backends = [p.get("backend") for p in payloads]
        if backends[0] != backends[1]:
            print(f"bench_compare: INCOMPARABLE — health runs from "
                  f"different backends ({backends[0]} vs {backends[1]})")
            return 0
        if any(p.get("smoke") for p in payloads):
            print("bench_compare: INCOMPARABLE — a --smoke health "
                  "artifact proves the harness, not the overhead")
            return 0
        rows, regressions = compare_health(old_payload, new_payload,
                                           args.tolerance)
        if not rows:
            print("  no common health metrics; nothing to compare")
            return 0
        flagged = {row[0] for row in regressions}
        width = max(len(name) for name, *_ in rows)
        for name, old, new, delta in rows:
            flag = "  REGRESSED" if name in flagged else ""
            print(f"  {name:<{width}}  {old:10.4f} -> {new:10.4f}  "
                  f"{delta * 100:+7.2f}%{flag}")
        if regressions:
            print(f"bench_compare: {len(regressions)} health metric(s) "
                  f"regressed past the {args.tolerance * 100:.1f}% "
                  f"tolerance")
            return 1
        return 0

    is_metrics = [p.get("kind") == "metrics_overhead" for p in payloads]
    if any(is_metrics):
        # Metrics-plane overhead gate over two BENCH_metrics*.json
        if not all(is_metrics):
            print("bench_compare: INCOMPARABLE — one artifact is a "
                  "metrics-overhead report, the other is not")
            return 0
        backends = [p.get("backend") for p in payloads]
        if backends[0] != backends[1]:
            print(f"bench_compare: INCOMPARABLE — metrics runs from "
                  f"different backends ({backends[0]} vs {backends[1]})")
            return 0
        if any(p.get("smoke") for p in payloads):
            print("bench_compare: INCOMPARABLE — a --smoke metrics "
                  "artifact proves the harness, not the overhead")
            return 0
        rows, regressions = compare_metrics(old_payload, new_payload,
                                            args.tolerance)
        if not rows:
            print("  no common metrics-overhead figures; nothing to "
                  "compare")
            return 0
        flagged = {row[0] for row in regressions}
        width = max(len(name) for name, *_ in rows)
        for name, old, new, delta in rows:
            flag = "  REGRESSED" if name in flagged else ""
            print(f"  {name:<{width}}  {old:10.4f} -> {new:10.4f}  "
                  f"{delta * 100:+7.2f}%{flag}")
        if regressions:
            print(f"bench_compare: {len(regressions)} metrics-plane "
                  f"figure(s) regressed past the "
                  f"{args.tolerance * 100:.1f}% tolerance")
            return 1
        return 0

    is_cluster = [p.get("kind") == "cluster" for p in payloads]
    if any(is_cluster):
        # Multi-host gate over two CLUSTER_r*.json artifacts
        if not all(is_cluster):
            print("bench_compare: INCOMPARABLE — one artifact is a "
                  "cluster run, the other is not")
            return 0
        backends = [p.get("backend") for p in payloads]
        if backends[0] != backends[1]:
            print(f"bench_compare: INCOMPARABLE — cluster runs from "
                  f"different backends ({backends[0]} vs {backends[1]})")
            return 0
        hosts = [p.get("hosts") for p in payloads]
        if hosts[0] != hosts[1]:
            print(f"bench_compare: INCOMPARABLE — different fleet sizes "
                  f"({hosts[0]} vs {hosts[1]} hosts)")
            return 0
        statuses = [p.get("status") for p in payloads]
        if any(s != "ok" for s in statuses):
            print(f"bench_compare: INCOMPARABLE — cluster run status "
                  f"{statuses[0]!r} vs {statuses[1]!r} (only ok runs "
                  f"carry comparable throughput)")
            return 0
        # Elastic shrink rounds only compare like-for-like: a round that
        # survived at 3 hosts ran a DIFFERENT fleet than one surviving
        # at 2 — its step rate and recovery cost measure another machine.
        # One-sided presence stays comparable on the legacy metrics.
        survivors = [(p.get("shrink_round") or {}).get("final_hosts")
                     for p in payloads]
        if all(s is not None for s in survivors) \
                and survivors[0] != survivors[1]:
            print(f"bench_compare: INCOMPARABLE — shrink rounds survived "
                  f"at different fleet sizes ({survivors[0]} vs "
                  f"{survivors[1]} hosts)")
            return 0
        rows, regressions = compare_cluster(old_payload, new_payload,
                                            args.tolerance)
        if not rows:
            print("  no common cluster metrics; nothing to compare")
            return 0
        flagged = {row[0] for row in regressions}
        width = max(len(name) for name, *_ in rows)
        for name, old, new, delta in rows:
            flag = "  REGRESSED" if name in flagged else ""
            print(f"  {name:<{width}}  {old:10.3f} -> {new:10.3f}  "
                  f"{delta * 100:+7.2f}%{flag}")
        if regressions:
            print(f"bench_compare: {len(regressions)} cluster metric(s) "
                  f"regressed past the {args.tolerance * 100:.1f}% "
                  f"tolerance")
            return 1
        return 0

    is_attr = [p.get("kind") == "attribution" for p in payloads]
    if any(is_attr):
        # Phase-budget gate over two attribution.json artifacts
        if not all(is_attr):
            print("bench_compare: INCOMPARABLE — one artifact is a phase "
                  "attribution, the other a benchmark payload")
            return 0
        backends = [p.get("backend") for p in payloads]
        if backends[0] != backends[1]:
            print(f"bench_compare: INCOMPARABLE — attributions from "
                  f"different backends ({backends[0]} vs {backends[1]})")
            return 0
        rows, regressions = compare_attribution(
            old_payload, new_payload, args.tolerance)
        if not rows:
            print("  no common phase budgets; nothing to compare")
            return 0
        flagged = {row[0] for row in regressions}
        width = max(len(name) for name, *_ in rows)
        for name, old, new, delta in rows:
            flag = "  REGRESSED" if name in flagged else ""
            print(f"  {name:<{width}}  {old:10.4f} -> {new:10.4f}  "
                  f"{delta * 100:+7.2f}%{flag}")
        if regressions:
            print(f"bench_compare: {len(regressions)} phase budget(s) grew "
                  f"past the {args.tolerance * 100:.1f}% tolerance")
            return 1
        return 0

    rows, regressions = compare(old_payload, new_payload, args.tolerance)
    if not rows:
        print("  no common steps/s cells; nothing to compare")
        return 0
    width = max(len(name) for name, *_ in rows)
    for name, old, new, delta in rows:
        flag = "  REGRESSED" if delta < -args.tolerance else ""
        print(f"  {name:<{width}}  {old:10.3f} -> {new:10.3f} steps/s  "
              f"{delta * 100:+7.2f}%{flag}")
    if regressions:
        print(f"bench_compare: {len(regressions)} cell(s) regressed past "
              f"the {args.tolerance * 100:.1f}% tolerance")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
