#!/usr/bin/env python3
"""Statistical accuracy-parity experiment: torch reference-style loop vs
this framework on the SAME data (BASELINE.md: "reproduce accuracy curve
within noise").

Two experiment families, selected by `--configs`:

* `mnist` — BASELINE.json config 2 shape: MNIST, n=11 workers, f=4 real
  Byzantine, GAR=median, attack=empire(1.1), momentum 0.9 at update, clip 2,
  constant lr. Both sides train `simples-full` (784-100-10 MLP). Synthetic
  MNIST saturates, so here the discriminative statistic is the AVERAGE LOSS
  trajectory at early checkpoints (steps 5/10/20/40) where the optimization
  is still in flight.

* `headline` — the paper's own CIFAR-10 Bulyan cell (reference
  `reproduce.py:165-209`, loop `attack.py:685-885`): `empire-cnn`, n=25
  workers, f=5, bulyan vs empire(1.1), momentum 0.99 at BOTH placements
  (update and worker), clip 5, constant lr. The synthetic CIFAR runs with a
  weak class signal (`BMT_SYNTH_SIGNAL`) chosen so a few-hundred-step run
  lands MID-RANGE top-1 (roughly 40-70%) — the parity statistic (final and
  max top-1 across seeds) sits at a value where failure was possible, unlike
  a saturating run. Paired accuracy curves at every eval checkpoint ride
  along in the JSON.

Both sides see the same deterministic synthetic data (no data egress in
this environment). True RNG-level trajectory matching is impossible across
frameworks (different PRNGs and batch orders — SURVEY.md §7 hard part 1);
the parity claim is STATISTICAL: the two mean statistics must agree within
the combined across-seed noise, noise = 2 * sqrt(std_t² + std_j²) (a ~95%
band on the difference of means for these sample sizes).

Writes ACCURACY_PARITY.json at the repo root.

Usage: python scripts/accuracy_parity.py [--steps 60] [--seeds 5]
           [--configs mnist,headline] [--headline-steps 300]
"""

import argparse
import json
import math
import os
import pathlib
import sys

os.environ.setdefault("BMT_SYNTH_TRAIN", "4096")
os.environ.setdefault("BMT_SYNTH_TEST", "512")

import numpy as np
import torch
import torch.nn as nn
import torch.nn.functional as F

_SCRIPTS_DIR = pathlib.Path(__file__).resolve().parent
sys.path.insert(0, str(_SCRIPTS_DIR.parent))
sys.path.insert(0, str(_SCRIPTS_DIR))

from byzantinemomentum_tpu.data import sources  # noqa: E402

N_WORKERS = 11
F_REAL = 4
N_HONEST = N_WORKERS - F_REAL
BATCH = 83
MOMENTUM = 0.9
CLIP = 2.0
LR = 0.1  # gentle enough that the loss decay is smooth (lr 0.5 with
# momentum 0.9 overshoots chaotically on the easy synthetic task, making
# transient checkpoints bimodal across seeds)
MNIST_NORM = (0.1307, 0.3081)


class SimplesFull(nn.Module):
    """Torch twin of `simples-full` (reference
    `experiments/models/simples.py:23-55`: 784-100-10, log-softmax)."""

    def __init__(self):
        super().__init__()
        self.f1 = nn.Linear(784, 100)
        self.f2 = nn.Linear(100, 10)

    def forward(self, x):
        x = F.relu(self.f1(x.flatten(1)))
        return F.log_softmax(self.f2(x), dim=1)


def _data():
    raw = sources.load_mnist("mnist")
    def prep(x):
        x = x.astype(np.float32) / 255.0
        return (x - MNIST_NORM[0]) / MNIST_NORM[1]
    return (prep(raw["train_x"]), raw["train_y"].astype(np.int64),
            prep(raw["test_x"]), raw["test_y"].astype(np.int64))


def _set_flat(model, vec):
    with torch.no_grad():
        offset = 0
        for p in model.parameters():
            num = p.numel()
            p.copy_(vec[offset:offset + num].view_as(p))
            offset += num


def _get_flat(model):
    with torch.no_grad():
        return torch.cat([p.flatten().clone() for p in model.parameters()])


def run_torch(seed, steps, momentum_at="update", nesterov=False):
    """Reference-style loop: sequential backprops, per-grad clip, empire
    attack, coordinate-wise lower median; momentum placement 'update'
    (reference `attack.py:836-838`) or 'worker' with per-worker buffers and
    the optional Nesterov parameter lookahead (`attack.py:757-783, 800-804`)."""
    train_x, train_y, test_x, test_y = _data()
    torch.manual_seed(seed)
    rng = np.random.default_rng(seed)
    model = SimplesFull()
    model.train()
    loss_fn = nn.NLLLoss()
    momentum_buf = None                     # at-update server buffer
    worker_bufs = [None] * N_HONEST         # at-worker per-worker buffers
    loss_curve = []
    for _ in range(steps):
        grads = []
        losses = []
        theta = _get_flat(model)
        for i in range(N_HONEST):
            if nesterov and worker_bufs[i] is not None:
                # Lookahead: shift params by -mu*lr*m_i before the backprop,
                # restore after (reference `attack.py:766-775`)
                _set_flat(model, theta - MOMENTUM * LR * worker_bufs[i])
            sel = rng.integers(0, len(train_x), BATCH)
            model.zero_grad()
            loss = loss_fn(model(torch.from_numpy(train_x[sel])),
                           torch.from_numpy(train_y[sel]))
            loss.backward()
            if nesterov:
                _set_flat(model, theta)
            g = torch.cat([p.grad.flatten() for p in model.parameters()])
            norm = g.norm().item()
            if norm > CLIP:
                g = g * (CLIP / norm)
            grads.append(g.detach().clone())
            losses.append(loss.item())
        loss_curve.append(float(np.mean(losses)))
        if momentum_at == "worker":
            # m_i <- mu*m_i + g_i; the buffers are what gets submitted
            # (reference `attack.py:800-804`)
            for i in range(N_HONEST):
                worker_bufs[i] = (grads[i] if worker_bufs[i] is None
                                  else MOMENTUM * worker_bufs[i] + grads[i])
            submitted = [b.clone() for b in worker_bufs]
        else:
            submitted = grads
        avg = torch.stack(submitted).mean(dim=0)
        byz = avg + 1.1 * (-avg)  # empire, factor 1.1
        stack = torch.stack(submitted + [byz] * F_REAL)
        n = stack.shape[0]
        agg = stack.sort(dim=0).values[(n - 1) // 2]  # lower median
        if momentum_at == "worker":
            update = agg  # defense output applied directly
        else:
            momentum_buf = (agg if momentum_buf is None
                            else MOMENTUM * momentum_buf + agg)
            update = momentum_buf
        _set_flat(model, _get_flat(model) - LR * update)
    model.eval()
    with torch.no_grad():
        pred = model(torch.from_numpy(test_x)).argmax(dim=1).numpy()
    return float((pred == test_y).mean()), loss_curve


def run_jax(seed, steps, tmp, momentum_at="update", nesterov=False):
    """The framework, through the standard driver CLI."""
    from byzantinemomentum_tpu.cli.attack import main
    resdir = pathlib.Path(tmp) / f"jax-{momentum_at}-{int(nesterov)}-{seed}"
    rc = main((["--momentum-nesterov"] if nesterov else []) +
              ["--dataset", "mnist", "--model", "simples-full",
               "--nb-workers", str(N_WORKERS),
               "--nb-decl-byz", str(F_REAL), "--nb-real-byz", str(F_REAL),
               "--gar", "median", "--attack", "empire",
               "--attack-args", "factor:1.1",
               "--momentum", str(MOMENTUM), "--momentum-at", momentum_at,
               "--gradient-clip", str(CLIP),
               "--batch-size", str(BATCH),
               "--learning-rate", str(LR), "--learning-rate-decay", "-1",
               "--nb-steps", str(steps),
               "--evaluation-delta", str(steps),
               "--nb-for-study", str(N_HONEST), "--nb-for-study-past", "1",
               "--batch-size-test", "128", "--batch-size-test-reps", "4",
               "--seed", str(seed),
               "--result-directory", str(resdir)])
    assert rc == 0
    rows = [l for l in (resdir / "eval").read_text().splitlines()[1:] if l]
    acc = float(rows[-1].split("\t")[1])
    study = [l for l in (resdir / "study").read_text().splitlines()[1:] if l]
    loss_curve = [float(l.split("\t")[2]) for l in study]
    return acc, loss_curve


# ------------------------------------------------------------------------- #
# Headline cell: CIFAR-10 empire-cnn, n=25 f=5, bulyan vs empire(1.1)
# (reference grid `reproduce.py:165-209`; loop `attack.py:685-885`)

H_N_WORKERS = 25
H_F = 5
H_N_HONEST = H_N_WORKERS - H_F
H_BATCH = 16        # shrunk from the grid's 50 to keep the 1-core torch side
H_MOMENTUM = 0.99   # tractable (VERDICT r3: shrink steps/batch, not model)
H_CLIP = 5.0
H_LR = 0.01
H_SIGNAL = "0.12"   # weak-signal synthetic CIFAR: mid-range top-1 at ~300
H_TRAIN = "8192"    # steps (see module docstring)
H_TEST = "1024"
CIFAR_MEAN = (0.4914, 0.4822, 0.4465)
CIFAR_STD = (0.2023, 0.1994, 0.2010)


def _headline_env():
    os.environ["BMT_SYNTH_TRAIN"] = H_TRAIN
    os.environ["BMT_SYNTH_TEST"] = H_TEST
    os.environ["BMT_SYNTH_SIGNAL"] = H_SIGNAL


def _cifar_data():
    raw = sources.load_cifar(10)
    mean = np.asarray(CIFAR_MEAN, np.float32)
    std = np.asarray(CIFAR_STD, np.float32)

    def prep(x):
        x = x.astype(np.float32) / 255.0
        return ((x - mean) / std).transpose(0, 3, 1, 2)  # NCHW
    return (prep(raw["train_x"]), raw["train_y"].astype(np.int64),
            prep(raw["test_x"]), raw["test_y"].astype(np.int64))


def run_torch_headline(seed, steps, momentum_at, eval_delta):
    """Reference-style loop on the headline cell: sequential backprops
    through one shared empire-cnn (train-mode BN batch stats + running-stat
    accumulation across workers, per-worker dropout draws — reference
    `experiments/model.py:246-248`), per-grad clip, empire attack, Bulyan,
    momentum at 'update' or 'worker' (reference `attack.py:799-810,
    832-839`).

    The CIFAR default transform includes a p=.5 random horizontal flip, and
    the reference applies the SAME transform list to the test set
    (reference `dataset.py:32-49`, quirk preserved by the framework's data
    layer) — the torch twin must flip too, or it trains on a strictly
    easier task (measured: 0.87 vs 0.45 final top-1 on the weak-signal
    synthetic set when the flips were missing on this side)."""
    from measure_torch_baseline import EmpireCnn, bulyan, flat_grad

    train_x, train_y, test_x, test_y = _cifar_data()
    torch.manual_seed(seed)
    rng = np.random.default_rng(seed)
    eval_rng = np.random.default_rng(seed + 99991)
    model = EmpireCnn()
    loss_fn = nn.NLLLoss()
    momentum_buf = None
    worker_bufs = [None] * H_N_HONEST
    acc_curve = {}

    def flipped(x_np, flips):
        # Copy: x_np may be a view into the dataset (test-set chunks), and
        # the in-place flip below must never write through to it
        x = torch.from_numpy(x_np.copy())
        if flips.any():
            x[flips] = torch.flip(x[flips], dims=[3])  # width axis, NCHW
        return x

    def evaluate(step):
        model.eval()
        with torch.no_grad():
            correct = 0
            for lo in range(0, len(test_x), 512):
                chunk = test_x[lo:lo + 512]
                fl = eval_rng.random(len(chunk)) < 0.5
                pred = model(flipped(chunk, fl))
                correct += int((pred.argmax(dim=1).numpy()
                                == test_y[lo:lo + 512]).sum())
        acc_curve[step] = correct / len(test_x)
        model.train()

    evaluate(0)
    for step in range(steps):
        grads = []
        for i in range(H_N_HONEST):
            sel = rng.integers(0, len(train_x), H_BATCH)
            fl = rng.random(H_BATCH) < 0.5
            model.zero_grad()
            loss = loss_fn(model(flipped(train_x[sel], fl)),
                           torch.from_numpy(train_y[sel]))
            loss.backward()
            g = flat_grad(model)
            norm = g.norm().item()
            if norm > H_CLIP:
                g = g * (H_CLIP / norm)
            grads.append(g.detach().clone())
        if momentum_at == "worker":
            for i in range(H_N_HONEST):
                worker_bufs[i] = (grads[i] if worker_bufs[i] is None
                                  else H_MOMENTUM * worker_bufs[i] + grads[i])
            submitted = [b.clone() for b in worker_bufs]
        else:
            submitted = grads
        avg = torch.stack(submitted).mean(dim=0)
        byz = avg + 1.1 * (-avg)  # empire, factor 1.1
        stack = torch.stack(submitted + [byz] * H_F)
        agg = bulyan(stack, H_F)
        if momentum_at == "worker":
            update = agg
        else:
            momentum_buf = (agg if momentum_buf is None
                            else H_MOMENTUM * momentum_buf + agg)
            update = momentum_buf
        with torch.no_grad():
            offset = 0
            for p in model.parameters():
                num = p.numel()
                p -= H_LR * update[offset:offset + num].view_as(p)
                offset += num
        if (step + 1) % eval_delta == 0 or step + 1 == steps:
            evaluate(step + 1)
    return acc_curve


def run_jax_headline(seed, steps, tmp, momentum_at, eval_delta):
    """The framework, through the standard driver CLI, on the headline cell."""
    from byzantinemomentum_tpu.cli.attack import main
    resdir = pathlib.Path(tmp) / f"jax-headline-{momentum_at}-{seed}"
    rc = main(["--dataset", "cifar10", "--model", "empire-cnn",
               "--nb-workers", str(H_N_WORKERS),
               "--nb-decl-byz", str(H_F), "--nb-real-byz", str(H_F),
               "--gar", "bulyan", "--attack", "empire",
               "--attack-args", "factor:1.1",
               "--momentum", str(H_MOMENTUM), "--momentum-at", momentum_at,
               "--gradient-clip", str(H_CLIP),
               "--batch-size", str(H_BATCH),
               "--learning-rate", str(H_LR), "--learning-rate-decay", "-1",
               "--nb-steps", str(steps),
               "--evaluation-delta", str(eval_delta),
               "--nb-for-study", "1", "--nb-for-study-past", "1",
               "--batch-size-test", "256", "--batch-size-test-reps", "4",
               "--seed", str(seed),
               "--result-directory", str(resdir)])
    assert rc == 0
    acc_curve = {}
    for line in (resdir / "eval").read_text().splitlines()[1:]:
        if line:
            step, acc = line.split("\t")
            acc_curve[int(step)] = float(acc)
    return acc_curve


def headline_config(args):
    """Run the headline cell for both momentum placements; parity on the
    final AND max top-1 (the reference's own headline analysis compares
    per-run max accuracies, `reproduce.py:258-366`)."""
    _headline_env()
    steps, eval_delta = args.headline_steps, args.headline_eval_delta
    seeds = list(range(1, args.headline_seeds + 1))
    out = []
    for momentum_at in ("update", "worker"):
        torch_curves = [run_torch_headline(s, steps, momentum_at, eval_delta)
                        for s in seeds]
        jax_curves = [run_jax_headline(s, steps, args.tmp, momentum_at,
                                       eval_delta)
                      for s in seeds]
        final = _compare([c[steps] for c in torch_curves],
                         [c[steps] for c in jax_curves], floor=0.04)
        max_acc = _compare([max(c.values()) for c in torch_curves],
                           [max(c.values()) for c in jax_curves], floor=0.04)
        saturated = (final["torch"]["mean"] > 0.95
                     and final["jax"]["mean"] > 0.95)
        out.append({
            "config": f"CIFAR-10 empire-cnn, n={H_N_WORKERS} f={H_F}, "
                      f"bulyan vs empire(1.1), momentum {H_MOMENTUM} at "
                      f"{momentum_at}, clip {H_CLIP}, lr {H_LR}, batch "
                      f"{H_BATCH}, {steps} steps, {len(seeds)} seeds, "
                      f"weak-signal synthetic CIFAR (BMT_SYNTH_SIGNAL="
                      f"{H_SIGNAL}, shared by both sides; mid-range top-1 — "
                      f"non-saturating by construction)",
            "accuracy_final": final,
            "accuracy_max": max_acc,
            "saturated": saturated,
            "curves": {
                "torch": [{str(k): v for k, v in c.items()}
                          for c in torch_curves],
                "jax": [{str(k): v for k, v in c.items()}
                        for c in jax_curves],
            },
            "parity": bool(final["parity"] and max_acc["parity"]
                           and not saturated),
        })
    return out


def _compare(t_vals, j_vals, floor):
    t = {"mean": float(np.mean(t_vals)),
         "std": float(np.std(t_vals, ddof=1)) if len(t_vals) > 1 else 0.0,
         "values": [float(v) for v in t_vals]}
    j = {"mean": float(np.mean(j_vals)),
         "std": float(np.std(j_vals, ddof=1)) if len(j_vals) > 1 else 0.0,
         "values": [float(v) for v in j_vals]}
    diff = abs(t["mean"] - j["mean"])
    noise = 2.0 * math.sqrt(t["std"] ** 2 + j["std"] ** 2)
    return {"torch": t, "jax": j, "diff": diff, "noise": noise,
            "parity": bool(diff <= max(noise, floor))}


def mnist_configs(args):
    seeds = list(range(1, args.seeds + 1))
    variants = (("update", False), ("worker", True))
    configs = []
    for momentum_at, nesterov in variants:
        torch_runs = [run_torch(s, args.steps, momentum_at, nesterov)
                      for s in seeds]
        jax_runs = [run_jax(s, args.steps, args.tmp, momentum_at, nesterov)
                    for s in seeds]
        accuracy = _compare([r[0] for r in torch_runs],
                            [r[0] for r in jax_runs], floor=0.02)
        checkpoints = [k for k in (5, 10, 20, 40) if k < args.steps]
        loss_at = {}
        for k in checkpoints:
            loss_at[str(k)] = _compare([r[1][k] for r in torch_runs],
                                       [r[1][k] for r in jax_runs],
                                       floor=0.05)  # 5% abs on NLL scale
        configs.append({
            "config": f"MNIST simples-full, n={N_WORKERS} f={F_REAL}, "
                      f"median vs empire(1.1), momentum {MOMENTUM} at "
                      f"{momentum_at}{' +nesterov' if nesterov else ''}, "
                      f"clip {CLIP}, lr {LR}, {args.steps} steps, "
                      f"{args.seeds} seeds, synthetic MNIST (deterministic, "
                      f"shared by both sides)",
            "accuracy": accuracy,
            "loss_at": loss_at,
            "parity": bool(accuracy["parity"]
                           and all(v["parity"] for v in loss_at.values())),
        })
    return configs


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--steps", type=int, default=60)
    parser.add_argument("--seeds", type=int, default=5)
    parser.add_argument("--configs", type=str, default="mnist,headline",
                        help="comma-separated subset of {mnist, headline}")
    parser.add_argument("--headline-steps", type=int, default=300)
    parser.add_argument("--headline-seeds", type=int, default=3)
    parser.add_argument("--headline-eval-delta", type=int, default=50)
    parser.add_argument("--tmp", type=str, default="/tmp/accuracy_parity")
    parser.add_argument("--merge", action="store_true",
                        help="keep entries of the other family already in "
                             "ACCURACY_PARITY.json instead of dropping them")
    args = parser.parse_args()
    which = {t.strip() for t in args.configs.split(",") if t.strip()}
    unknown = which - {"mnist", "headline"}
    if unknown or not which:
        parser.error(f"--configs must name a non-empty subset of "
                     f"{{mnist, headline}}; got {sorted(unknown) or 'nothing'}"
                     " (a typo here would otherwise overwrite "
                     "ACCURACY_PARITY.json with a vacuous parity:true)")

    path = pathlib.Path(__file__).resolve().parent.parent / "ACCURACY_PARITY.json"
    configs = []
    if args.merge and path.is_file():
        old = json.loads(path.read_text()).get("configs", [])
        keep_mnist = "mnist" not in which
        keep_headline = "headline" not in which
        for c in old:
            is_headline = c["config"].startswith("CIFAR")
            if (keep_headline and is_headline) or (keep_mnist and not is_headline):
                configs.append(c)
    if "mnist" in which:
        configs.extend(mnist_configs(args))
    if "headline" in which:
        configs.extend(headline_config(args))
    # The parity harness runs BOTH sides on the shared deterministic
    # synthetic data by design (no data egress here); say so in the
    # artifact instead of only in the config strings
    for c in configs:
        c.setdefault("synthetic_data", True)
    out = {"configs": configs,
           "parity": bool(all(c["parity"] for c in configs)),
           "synthetic_data": True}
    path.write_text(json.dumps(out, indent=2) + "\n")
    print(json.dumps({k: v for k, v in out.items() if k != "configs"}
                     | {"per_config": [{"config": c["config"],
                                        "parity": c["parity"]}
                                       for c in configs]}))


if __name__ == "__main__":
    main()
