#!/usr/bin/env python3
"""Statistical accuracy-parity experiment: torch reference-style loop vs
this framework on the SAME data (BASELINE.md: "reproduce accuracy curve
within noise").

Config = BASELINE.json config 2 shape: MNIST, n=11 workers, f=4 real
Byzantine, GAR=median, attack=empire(1.1), momentum 0.9 at update, clip 2,
constant lr. Both sides train `simples-full` (784-100-10 MLP) on the same
deterministic synthetic MNIST (no data egress in this environment), for
`--steps` steps and `--seeds` seeds each, evaluating top-1 accuracy on the
same test split. True RNG-level trajectory matching is impossible across
frameworks (different PRNGs and batch orders — SURVEY.md §7 hard part 1);
the parity claim is STATISTICAL: the two mean final accuracies must agree
within the combined across-seed noise.

Two statistics, both across seeds:
* final top-1 accuracy (synthetic MNIST saturates, so this mostly checks
  that neither side diverges under attack), and
* the AVERAGE LOSS trajectory at early checkpoints (steps 5/10/20/40),
  where the optimization is still in flight — the discriminative part: a
  momentum/clip/aggregation semantics mismatch shows up here.

Writes ACCURACY_PARITY.json at the repo root:
  {"accuracy": {"torch": {...}, "jax": {...}, "diff", "noise", "parity"},
   "loss_at": {"5": {...}, ...}, "parity": true|false}
with noise = 2 * sqrt(std_t² + std_j²) (a ~95% band on the difference of
means for these sample sizes).

Usage: python scripts/accuracy_parity.py [--steps 60] [--seeds 5]
"""

import argparse
import json
import math
import os
import pathlib
import sys

os.environ.setdefault("BMT_SYNTH_TRAIN", "4096")
os.environ.setdefault("BMT_SYNTH_TEST", "512")

import numpy as np
import torch
import torch.nn as nn
import torch.nn.functional as F

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from byzantinemomentum_tpu.data import sources  # noqa: E402

N_WORKERS = 11
F_REAL = 4
N_HONEST = N_WORKERS - F_REAL
BATCH = 83
MOMENTUM = 0.9
CLIP = 2.0
LR = 0.1  # gentle enough that the loss decay is smooth (lr 0.5 with
# momentum 0.9 overshoots chaotically on the easy synthetic task, making
# transient checkpoints bimodal across seeds)
MNIST_NORM = (0.1307, 0.3081)


class SimplesFull(nn.Module):
    """Torch twin of `simples-full` (reference
    `experiments/models/simples.py:23-55`: 784-100-10, log-softmax)."""

    def __init__(self):
        super().__init__()
        self.f1 = nn.Linear(784, 100)
        self.f2 = nn.Linear(100, 10)

    def forward(self, x):
        x = F.relu(self.f1(x.flatten(1)))
        return F.log_softmax(self.f2(x), dim=1)


def _data():
    raw = sources.load_mnist("mnist")
    def prep(x):
        x = x.astype(np.float32) / 255.0
        return (x - MNIST_NORM[0]) / MNIST_NORM[1]
    return (prep(raw["train_x"]), raw["train_y"].astype(np.int64),
            prep(raw["test_x"]), raw["test_y"].astype(np.int64))


def _set_flat(model, vec):
    with torch.no_grad():
        offset = 0
        for p in model.parameters():
            num = p.numel()
            p.copy_(vec[offset:offset + num].view_as(p))
            offset += num


def _get_flat(model):
    with torch.no_grad():
        return torch.cat([p.flatten().clone() for p in model.parameters()])


def run_torch(seed, steps, momentum_at="update", nesterov=False):
    """Reference-style loop: sequential backprops, per-grad clip, empire
    attack, coordinate-wise lower median; momentum placement 'update'
    (reference `attack.py:836-838`) or 'worker' with per-worker buffers and
    the optional Nesterov parameter lookahead (`attack.py:757-783, 800-804`)."""
    train_x, train_y, test_x, test_y = _data()
    torch.manual_seed(seed)
    rng = np.random.default_rng(seed)
    model = SimplesFull()
    model.train()
    loss_fn = nn.NLLLoss()
    momentum_buf = None                     # at-update server buffer
    worker_bufs = [None] * N_HONEST         # at-worker per-worker buffers
    loss_curve = []
    for _ in range(steps):
        grads = []
        losses = []
        theta = _get_flat(model)
        for i in range(N_HONEST):
            if nesterov and worker_bufs[i] is not None:
                # Lookahead: shift params by -mu*lr*m_i before the backprop,
                # restore after (reference `attack.py:766-775`)
                _set_flat(model, theta - MOMENTUM * LR * worker_bufs[i])
            sel = rng.integers(0, len(train_x), BATCH)
            model.zero_grad()
            loss = loss_fn(model(torch.from_numpy(train_x[sel])),
                           torch.from_numpy(train_y[sel]))
            loss.backward()
            if nesterov:
                _set_flat(model, theta)
            g = torch.cat([p.grad.flatten() for p in model.parameters()])
            norm = g.norm().item()
            if norm > CLIP:
                g = g * (CLIP / norm)
            grads.append(g.detach().clone())
            losses.append(loss.item())
        loss_curve.append(float(np.mean(losses)))
        if momentum_at == "worker":
            # m_i <- mu*m_i + g_i; the buffers are what gets submitted
            # (reference `attack.py:800-804`)
            for i in range(N_HONEST):
                worker_bufs[i] = (grads[i] if worker_bufs[i] is None
                                  else MOMENTUM * worker_bufs[i] + grads[i])
            submitted = [b.clone() for b in worker_bufs]
        else:
            submitted = grads
        avg = torch.stack(submitted).mean(dim=0)
        byz = avg + 1.1 * (-avg)  # empire, factor 1.1
        stack = torch.stack(submitted + [byz] * F_REAL)
        n = stack.shape[0]
        agg = stack.sort(dim=0).values[(n - 1) // 2]  # lower median
        if momentum_at == "worker":
            update = agg  # defense output applied directly
        else:
            momentum_buf = (agg if momentum_buf is None
                            else MOMENTUM * momentum_buf + agg)
            update = momentum_buf
        _set_flat(model, _get_flat(model) - LR * update)
    model.eval()
    with torch.no_grad():
        pred = model(torch.from_numpy(test_x)).argmax(dim=1).numpy()
    return float((pred == test_y).mean()), loss_curve


def run_jax(seed, steps, tmp, momentum_at="update", nesterov=False):
    """The framework, through the standard driver CLI."""
    from byzantinemomentum_tpu.cli.attack import main
    resdir = pathlib.Path(tmp) / f"jax-{momentum_at}-{int(nesterov)}-{seed}"
    rc = main((["--momentum-nesterov"] if nesterov else []) +
              ["--dataset", "mnist", "--model", "simples-full",
               "--nb-workers", str(N_WORKERS),
               "--nb-decl-byz", str(F_REAL), "--nb-real-byz", str(F_REAL),
               "--gar", "median", "--attack", "empire",
               "--attack-args", "factor:1.1",
               "--momentum", str(MOMENTUM), "--momentum-at", momentum_at,
               "--gradient-clip", str(CLIP),
               "--batch-size", str(BATCH),
               "--learning-rate", str(LR), "--learning-rate-decay", "-1",
               "--nb-steps", str(steps),
               "--evaluation-delta", str(steps),
               "--nb-for-study", str(N_HONEST), "--nb-for-study-past", "1",
               "--batch-size-test", "128", "--batch-size-test-reps", "4",
               "--seed", str(seed),
               "--result-directory", str(resdir)])
    assert rc == 0
    rows = [l for l in (resdir / "eval").read_text().splitlines()[1:] if l]
    acc = float(rows[-1].split("\t")[1])
    study = [l for l in (resdir / "study").read_text().splitlines()[1:] if l]
    loss_curve = [float(l.split("\t")[2]) for l in study]
    return acc, loss_curve


def _compare(t_vals, j_vals, floor):
    t = {"mean": float(np.mean(t_vals)),
         "std": float(np.std(t_vals, ddof=1)) if len(t_vals) > 1 else 0.0,
         "values": [float(v) for v in t_vals]}
    j = {"mean": float(np.mean(j_vals)),
         "std": float(np.std(j_vals, ddof=1)) if len(j_vals) > 1 else 0.0,
         "values": [float(v) for v in j_vals]}
    diff = abs(t["mean"] - j["mean"])
    noise = 2.0 * math.sqrt(t["std"] ** 2 + j["std"] ** 2)
    return {"torch": t, "jax": j, "diff": diff, "noise": noise,
            "parity": bool(diff <= max(noise, floor))}


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--steps", type=int, default=60)
    parser.add_argument("--seeds", type=int, default=5)
    parser.add_argument("--tmp", type=str, default="/tmp/accuracy_parity")
    args = parser.parse_args()

    seeds = list(range(1, args.seeds + 1))
    variants = (("update", False), ("worker", True))
    configs = []
    for momentum_at, nesterov in variants:
        torch_runs = [run_torch(s, args.steps, momentum_at, nesterov)
                      for s in seeds]
        jax_runs = [run_jax(s, args.steps, args.tmp, momentum_at, nesterov)
                    for s in seeds]
        accuracy = _compare([r[0] for r in torch_runs],
                            [r[0] for r in jax_runs], floor=0.02)
        checkpoints = [k for k in (5, 10, 20, 40) if k < args.steps]
        loss_at = {}
        for k in checkpoints:
            loss_at[str(k)] = _compare([r[1][k] for r in torch_runs],
                                       [r[1][k] for r in jax_runs],
                                       floor=0.05)  # 5% abs on NLL scale
        configs.append({
            "config": f"MNIST simples-full, n={N_WORKERS} f={F_REAL}, "
                      f"median vs empire(1.1), momentum {MOMENTUM} at "
                      f"{momentum_at}{' +nesterov' if nesterov else ''}, "
                      f"clip {CLIP}, lr {LR}, {args.steps} steps, "
                      f"{args.seeds} seeds, synthetic MNIST (deterministic, "
                      f"shared by both sides)",
            "accuracy": accuracy,
            "loss_at": loss_at,
            "parity": bool(accuracy["parity"]
                           and all(v["parity"] for v in loss_at.values())),
        })
    out = {"configs": configs,
           "parity": bool(all(c["parity"] for c in configs))}
    path = pathlib.Path(__file__).resolve().parent.parent / "ACCURACY_PARITY.json"
    path.write_text(json.dumps(out, indent=2) + "\n")
    print(json.dumps(out))


if __name__ == "__main__":
    main()
