#!/usr/bin/env python3
"""Measure the numerics flight recorder's steps/s overhead: health on vs
off on the CPU smoke config, paired windows, median of per-pair ratios.

The health vector (`engine/health.py`) rides the compiled step as extra
metric outputs — the claim is that it stays within the telemetry
discipline (PR 3 measured the recorder itself at -1.4% steps/s; the
acceptance bound here is 3%). On a 1-core host an on/off A/B of a
per-step code path is unmeasurable with independent best-of-N windows
(±10% drift swamps it — PERF_NOTES r13), so this harness interleaves
PAIRED off/on chunks and reports the median of the per-pair rate ratios:
drift hits both sides of a pair equally and cancels in the ratio.

Writes `BENCH_health.json` (`"kind": "health_overhead"`) —
`scripts/bench_compare.py` gates a pair of these (overhead growth past
tolerance over a 1-point floor fails; steps/s drops fail), and
`scripts/bench_history.py` renders the per-round trajectory from
committed `BENCH_health_r*.json` artifacts.

Usage:
  python scripts/health_overhead.py [--smoke] [--out BENCH_health.json]
"""

import argparse
import json
import pathlib
import statistics
import sys
import time

ROOT = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(ROOT))

__all__ = ["measure", "main"]

# The CPU smoke configuration (the driver e2e tests' scale: the
# reference's n=11 worker grid on the full MNIST conv model)
SMOKE = {"nb_workers": 11, "nb_decl_byz": 2, "nb_real_byz": 2,
         "batch": 8, "gar": "median", "attack": "empire",
         "attack_factor": 1.1, "momentum_at": "worker", "lr": 0.05}


def _build(health, seed=11):
    import jax

    from byzantinemomentum_tpu import attacks, losses, models, ops
    from byzantinemomentum_tpu.engine import EngineConfig, build_engine

    cfg = EngineConfig(
        nb_workers=SMOKE["nb_workers"], nb_decl_byz=SMOKE["nb_decl_byz"],
        nb_real_byz=SMOKE["nb_real_byz"],
        nb_for_study=SMOKE["nb_workers"], nb_for_study_past=2,
        momentum=0.9, momentum_at=SMOKE["momentum_at"], health=health)
    engine = build_engine(
        cfg=cfg, model_def=models.build("simples-full"),
        loss=losses.Loss("nll"), criterion=losses.Criterion("top-k"),
        defenses=[(ops.gars[SMOKE["gar"]], 1.0, {})],
        attack=attacks.attacks[SMOKE["attack"]],
        attack_kwargs={"factor": SMOKE["attack_factor"]})
    state = engine.init(jax.random.PRNGKey(seed))
    return engine, state


def measure(pairs=12, steps_per_chunk=8, seed=11):
    """Paired off/on chunk timing; returns the artifact payload dict."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    rng = np.random.default_rng(seed)
    engines = {}
    states = {}
    for health in (False, True):
        engines[health], states[health] = _build(health, seed=seed)

    S = engines[False].cfg.nb_sampled
    B = SMOKE["batch"]
    M = steps_per_chunk
    xs = jnp.asarray(rng.normal(size=(M, S, B, 28, 28, 1))
                     .astype(np.float32))
    ys = jnp.asarray(rng.integers(0, 10, size=(M, S, B)).astype(np.int32))
    lrs = jnp.full((M,), SMOKE["lr"], jnp.float32)

    def chunk(health):
        t0 = time.perf_counter()
        state, metrics = engines[health].train_multi(
            states[health], xs, ys, lrs)
        jax.block_until_ready(state.theta)
        states[health] = state
        return M / (time.perf_counter() - t0)

    # Warm both programs (compiles) outside any timed window
    for health in (False, True):
        chunk(health)
        chunk(health)

    ratios, off_rates, on_rates = [], [], []
    for pair in range(pairs):
        # Alternate the within-pair order: linear drift (thermal, a
        # neighboring process) then biases half the pairs up and half
        # down, and the median ratio cancels it
        order = (False, True) if pair % 2 == 0 else (True, False)
        rates = {}
        for health in order:
            rates[health] = chunk(health)
        off_rates.append(rates[False])
        on_rates.append(rates[True])
        ratios.append(rates[True] / rates[False])

    overhead = 1.0 - statistics.median(ratios)
    return {
        "kind": "health_overhead",
        "backend": jax.default_backend(),
        "config": dict(SMOKE, steps_per_chunk=M, pairs=pairs),
        "steps_per_sec_off": round(statistics.median(off_rates), 3),
        "steps_per_sec_on": round(statistics.median(on_rates), 3),
        "overhead_frac": round(overhead, 5),
        "overhead_ok": overhead <= 0.03,  # the PR 15 acceptance bound
    }


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="health_overhead",
        description="Measure --health steps/s overhead (paired on/off "
                    "windows, median of per-pair ratios) and write "
                    "BENCH_health.json")
    # 48 pairs of 6-step chunks: measured resolution ~±0.3% on the
    # 1-core build host (8-step chunks at 12-30 pairs drifted ±1.5% —
    # the pair count, not the chunk length, buys the precision)
    parser.add_argument("--pairs", type=int, default=48)
    parser.add_argument("--steps-per-chunk", type=int, default=6)
    parser.add_argument("--smoke", action="store_true",
                        help="tiny CI form: 3 pairs of 4-step chunks, "
                             "no acceptance gate on the (noisy) number")
    parser.add_argument("--out", type=str, default=None,
                        help="artifact path (default BENCH_health.json "
                             "at the repo root)")
    args = parser.parse_args(argv)

    if args.smoke:
        args.pairs, args.steps_per_chunk = 3, 4
    payload = measure(pairs=args.pairs,
                      steps_per_chunk=args.steps_per_chunk)
    if args.smoke:
        payload["smoke"] = True
    out = pathlib.Path(args.out) if args.out else ROOT / "BENCH_health.json"
    out.write_text(json.dumps(payload, indent=2) + "\n")
    print(json.dumps(payload))
    # The acceptance bound only gates a full measurement: the smoke form
    # exists to prove the harness runs, not to measure on a loaded core
    if not args.smoke and not payload["overhead_ok"]:
        print(f"health_overhead: overhead {payload['overhead_frac']:.2%} "
              f"exceeds the 3% acceptance bound", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
