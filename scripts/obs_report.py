#!/usr/bin/env python3
"""One-page text summary of any run directory's telemetry.

Thin wrapper over `byzantinemomentum_tpu.obs.report` (also reachable as
`python -m byzantinemomentum_tpu.obs <run_dir>`): heartbeat freshness,
counters, span cost stats, throughput gauges and the resilience timeline
(faults / rollbacks / restarts) — pure stdlib, no accelerator init, works
on live and dead runs alike.

Usage: python scripts/obs_report.py <run_dir>
"""

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from byzantinemomentum_tpu.obs.report import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
