#!/usr/bin/env python3
"""Summarize stale→dead / stale→alive edge durations from recorded fleet
timelines and recommend a bounded-wait window for the straggler-host
policy.

A cluster launcher (`byzantinemomentum_tpu/cluster/launcher.py`) emits a
`liveness_transition` event every time a host's status edge flips
(alive/stale/dead/unknown — `obs/trace/fleet.py` joins them into the
fleet timeline). The ROADMAP's straggler-host rung needs a data-driven
answer to ONE question before a policy can exist: when a host goes
stale, how long is it worth waiting before treating it as dead? Wait too
little and every GC pause / slow poll kills a healthy host (a fleet
teardown + restart each time); wait too long and a genuinely dead host
stalls recovery by exactly the window.

This script measures both sides from recorded runs: each host's stale
episodes are extracted from the transition stream, split by how they
resolved (back to `alive` — a straggler that recovered — vs `dead`), and
the recommended window is the 95th percentile of the observed recovery
durations with a 1.25x safety margin — long enough to cover ~95% of
recoveries, with the expected cost per actually-dead host (the window
itself) reported next to it so the trade is explicit. Episodes still
open when the stream ends are counted as censored, never guessed.

Usage:
  python scripts/stale_edges.py RUN_DIR [RUN_DIR ...] [--json]

Each RUN_DIR is a cluster run's result directory (its `telemetry.jsonl`
holds the launcher stream); a direct path to a telemetry .jsonl file
works too. Prints a human summary plus one parseable
`stale-edges: {...}` line.
"""

import argparse
import json
import math
import pathlib
import sys

ROOT = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(ROOT))

from byzantinemomentum_tpu.obs.recorder import load_records  # noqa: E402

__all__ = ["stale_episodes", "summarize", "recommend_window",
           "recommendation", "main"]

# Safety margin over the observed recovery tail: clocks jitter, polls
# quantize, and the recorded runs undersample the tail
MARGIN = 1.25


def stale_episodes(records):
    """Split a launcher telemetry stream into per-host stale episodes.

    Returns `{"recovered": [durations], "died": [durations],
    "censored": int}` — durations in seconds from the host's `-> stale`
    edge to the edge that resolved it (`-> alive` = recovered,
    `-> dead` = died; a `-> unknown` edge or end-of-stream censors the
    episode).
    """
    open_since = {}   # host -> t of the -> stale edge
    recovered, died = [], []
    censored = 0
    for record in records:
        if record.get("kind") != "event" \
                or record.get("name") != "liveness_transition":
            continue
        data = record.get("data") or {}
        host, to = data.get("host"), data.get("to")
        t = record.get("t")
        if host is None or t is None:
            continue
        started = open_since.pop(host, None)
        if to == "stale":
            open_since[host] = float(t)
            continue
        if started is None:
            continue
        duration = max(0.0, float(t) - started)
        if to == "alive":
            recovered.append(duration)
        elif to == "dead":
            died.append(duration)
        else:
            censored += 1  # -> unknown: the signal vanished, not resolved
    censored += len(open_since)
    return {"recovered": sorted(recovered), "died": sorted(died),
            "censored": censored}


def _percentile(values, q):
    """Nearest-rank percentile of a sorted list (None when empty)."""
    if not values:
        return None
    rank = max(1, math.ceil(q * len(values)))
    return values[rank - 1]


def _stats(values):
    if not values:
        return None
    return {"count": len(values),
            "min_s": round(values[0], 3),
            "median_s": round(_percentile(values, 0.5), 3),
            "p95_s": round(_percentile(values, 0.95), 3),
            "max_s": round(values[-1], 3)}


def recommend_window(episodes):
    """The bounded-wait recommendation from measured episodes.

    `p95(recovered) * MARGIN` when recoveries were observed — the window
    that covers ~95% of observed stragglers; with only deaths on record
    there is nothing worth waiting for, so half the fastest observed
    death keeps the wait strictly below every measured failure. None
    when the stream carries no resolved episodes at all.
    """
    recovered = episodes["recovered"]
    died = episodes["died"]
    if recovered:
        return round(_percentile(recovered, 0.95) * MARGIN, 3)
    if died:
        return round(died[0] / 2.0, 3)
    return None


def recommendation(episodes):
    """The machine-readable recommendation block the straggler policy
    consumes directly (`cluster/straggler.py::resolve_wait_bound`):
    the window, WHAT it was derived from, and the evidence counts —
    censored episodes reported next to the p95 they were excluded from,
    so a consumer can see how much of the record the number ignores."""
    recovered = episodes["recovered"]
    died = episodes["died"]
    if recovered:
        basis = "p95_recoveries"
    elif died:
        basis = "half_fastest_death"
    else:
        basis = None
    block = {"wait_s": recommend_window(episodes), "basis": basis,
             "recoveries": len(recovered), "deaths": len(died),
             "censored": int(episodes.get("censored") or 0)}
    if basis == "p95_recoveries":
        block["margin"] = MARGIN
        block["p95_recovery_s"] = round(_percentile(recovered, 0.95), 3)
    return block


def summarize(run_dirs):
    """The aggregate summary over one or more run directories (or direct
    telemetry file paths)."""
    merged = {"recovered": [], "died": [], "censored": 0}
    runs = 0
    for run in run_dirs:
        records = load_records(pathlib.Path(run))
        if not records:
            continue
        runs += 1
        episodes = stale_episodes(records)
        merged["recovered"].extend(episodes["recovered"])
        merged["died"].extend(episodes["died"])
        merged["censored"] += episodes["censored"]
    merged["recovered"].sort()
    merged["died"].sort()
    window = recommend_window(merged)
    return {
        "kind": "stale_edges",
        "runs": runs,
        "stale_to_alive": _stats(merged["recovered"]),
        "stale_to_dead": _stats(merged["died"]),
        "censored": merged["censored"],
        "recommended_wait_s": window,
        # The explicit trade: a dead host costs the whole window before
        # recovery starts; a recovery inside the window costs nothing
        "wait_cost_per_dead_host_s": window,
        # Structured form of the same recommendation, for machine
        # consumers (`--straggler-edges` hands this file straight to the
        # cluster launcher)
        "recommendation": recommendation(merged),
    }


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="stale_edges",
        description="Summarize stale->dead / stale->alive edge durations "
                    "from recorded fleet timelines and print a "
                    "recommended bounded-wait window")
    parser.add_argument("runs", nargs="+",
                        help="cluster run directories (or telemetry "
                             ".jsonl files) holding launcher "
                             "liveness_transition events")
    parser.add_argument("--json", action="store_true",
                        help="print only the JSON summary line")
    args = parser.parse_args(argv)

    summary = summarize(args.runs)
    line = "stale-edges: " + json.dumps(summary, sort_keys=True)
    if args.json:
        print(line)
        return 0 if summary["runs"] else 1
    if not summary["runs"]:
        print("stale_edges: no telemetry records found under the given "
              "paths")
        return 1
    print(f"stale edges over {summary['runs']} run(s):")
    for label, key in (("stale -> alive (recovered)", "stale_to_alive"),
                       ("stale -> dead  (died)", "stale_to_dead")):
        stats = summary[key]
        if stats is None:
            print(f"  {label:<28} (none observed)")
            continue
        print(f"  {label:<28} x{stats['count']}  min {stats['min_s']}s  "
              f"median {stats['median_s']}s  p95 {stats['p95_s']}s  "
              f"max {stats['max_s']}s")
    if summary["censored"]:
        print(f"  censored episodes            x{summary['censored']} "
              f"(unresolved at end of stream)")
    if summary["recommended_wait_s"] is None:
        print("  no resolved episodes; no recommendation")
    else:
        print(f"  recommended bounded wait: {summary['recommended_wait_s']}s"
              f" (p95 of recoveries x{MARGIN}; a dead host costs the "
              f"window before recovery starts)")
    print(line)
    return 0


if __name__ == "__main__":
    sys.exit(main())
