#!/usr/bin/env python3
"""On-hardware self-check: run the GAR kernels on the REAL TPU (where the
Pallas fast paths engage — the pytest suite pins the CPU backend) and
compare every rule against its jnp fallback and against a torch-CPU oracle
on the same inputs, NaN rows included.

Tolerances: the selection decisions must agree exactly; the averaged values
may differ by float reassociation (matmul-formulated means) at ~1e-6.

Usage: python scripts/tpu_selfcheck.py [--n 25] [--d 131072] [--f 5]
Exits non-zero on any mismatch; prints one summary line per rule.
"""

import argparse
import os
import pathlib
import sys

import numpy as np

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from byzantinemomentum_tpu import ops  # noqa: E402

RULES = ("average", "median", "trmean", "phocas", "meamed", "krum",
         "bulyan", "aksel", "cge")


def torch_oracle(name, g, f):
    """Reference-semantics oracle in torch (mirrors tests/reference_oracles
    for the subset used here); None if not implemented for `name`."""
    import torch

    t = torch.from_numpy(np.asarray(g))
    n = t.shape[0]
    if name == "average":
        return t.mean(dim=0).numpy()
    if name == "median":
        return t.sort(dim=0).values[(n - 1) // 2].numpy()
    if name == "trmean":
        return t.sort(dim=0).values[f:n - f].mean(dim=0).numpy()
    return None


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--n", type=int, default=25)
    parser.add_argument("--d", type=int, default=131072)
    parser.add_argument("--f", type=int, default=5)
    parser.add_argument("--nan-frac", type=float, default=0.01)
    args = parser.parse_args()

    backend = jax.default_backend()
    print(f"backend: {backend} ({jax.devices()[0].device_kind})")

    rng = np.random.default_rng(0)
    g = rng.standard_normal((args.n, args.d)).astype(np.float32)
    g[rng.random(g.shape) < args.nan_frac] = np.nan
    # Keep enough finite rows for every rule's contract
    g[: args.n - args.f] = np.nan_to_num(g[: args.n - args.f], nan=0.0)
    gj = jnp.asarray(g)

    failures = 0
    for name in RULES:
        gar = ops.gars[name]
        if gar.check(gradients=gj, f=args.f) is not None:
            print(f"{name:8s} SKIP (constraint at n={args.n}, f={args.f})")
            continue
        fast = np.asarray(jax.jit(  # bmt: noqa[BMT-E03] fresh wrapper intended: BMT_NO_PALLAS is trace-time state, a cached trace would ignore the toggle below
            lambda G: gar.unchecked(G, f=args.f))(gj))
        os.environ["BMT_NO_PALLAS"] = "1"
        slow = np.asarray(jax.jit(  # bmt: noqa[BMT-E03] fresh wrapper intended: must retrace with the pallas tier disabled
            lambda G: gar.unchecked(G, f=args.f))(gj))
        del os.environ["BMT_NO_PALLAS"]

        def norm(x):
            return np.nan_to_num(x, nan=7e9, posinf=8e9, neginf=-8e9)

        ok_fb = np.allclose(norm(fast), norm(slow), rtol=1e-5, atol=1e-6)
        oracle = torch_oracle(name, g, args.f)
        ok_or = (np.allclose(norm(fast), norm(oracle), rtol=1e-5, atol=1e-6)
                 if oracle is not None else None)
        status = "OK" if ok_fb and ok_or in (True, None) else "FAIL"
        failures += status == "FAIL"
        extra = "" if oracle is None else f" oracle={'OK' if ok_or else 'FAIL'}"
        print(f"{name:8s} {status}  vs-fallback="
              f"{'OK' if ok_fb else 'FAIL'}{extra}")
    if failures:
        raise SystemExit(f"{failures} rule(s) mismatched on {backend}")
    print("all rules consistent on", backend)


if __name__ == "__main__":
    main()
