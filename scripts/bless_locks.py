#!/usr/bin/env python3
"""(Re)bless the lock-hierarchy goldens.

Writes `tests/goldens/locks.json`: the whole-program lock census from
`analysis/locks.py` — every named lock the interprocedural sweep can
see, every acquisition-order edge (`held -> taken`), and the
topological order those edges induce — plus the python toolchain
coordinate the census is comparable under. The lint tier's gate
(`python -m byzantinemomentum_tpu.analysis --check-locks`) fails on any
unexplained change — run THIS script only when a lock-hierarchy change
is intentional and reviewed, and commit the diff with the change that
caused it.

Locks and edges the sweep no longer derives are PRUNED (the file is the
census, nothing else) and reported, so stale names cannot linger.

Idempotent: blessing twice under one toolchain is byte-identical
(sorted keys, no timestamps). Pure AST — no jax import, no backend.

Usage: python scripts/bless_locks.py [--out PATH] [--check]
"""

import argparse
import json
import pathlib
import sys

ROOT = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(ROOT))

from byzantinemomentum_tpu.analysis import locks  # noqa: E402


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--out", type=str, default=None,
                        help="goldens path (default "
                             "tests/goldens/locks.json)")
    parser.add_argument("--check", action="store_true",
                        help="only report drift against the existing "
                             "goldens; do not rewrite")
    args = parser.parse_args()
    path = pathlib.Path(args.out) if args.out else locks.GOLDEN_PATH

    if args.check:
        report = locks.check(path)
        print(json.dumps(report, indent=2, sort_keys=True))
        return 0 if report["ok"] else 1

    payload, changed, old = locks.bless(path)
    old_locks = set((old or {}).get("locks", ()))
    old_edges = set((old or {}).get("edges", ()))
    pruned = sorted((old_locks - set(payload["locks"]))
                    | (old_edges - set(payload["edges"])))
    added = sorted((set(payload["locks"]) - old_locks)
                   | (set(payload["edges"]) - old_edges))
    print(f"blessed {len(payload['locks'])} locks, "
          f"{len(payload['edges'])} edges -> {path}"
          + (" (changed)" if changed else " (unchanged)"))
    if pruned:
        print(f"pruned {len(pruned)} stale name(s)/edge(s) the sweep no "
              f"longer derives:")
        for key in pruned:
            print(f"  pruned: {key}")
    if added:
        print(f"added {len(added)} new name(s)/edge(s):")
        for key in added:
            print(f"  added: {key}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
