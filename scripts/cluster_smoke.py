#!/usr/bin/env python3
"""The multi-host chaos acceptance, as one command: an N-host CPU-backend
fleet trains uninterrupted; a second identical fleet has one host
SIGKILLed mid-step by the system-level FaultPlan, recovers through the
launcher (manifest-agreed restart step, off-slice mirror, auto-resume —
the dead host's local directory is deleted at teardown), and the resumed
study CSV must be BIT-IDENTICAL to the uninterrupted run's.

Writes a `CLUSTER.json` artifact (`"kind": "cluster"`) merging the
uninterrupted fleet's throughput + census/zero-recompile verdicts with
the chaos fleet's recovery record and the bit-identity bit — the
artifact `scripts/bench_compare.py` gates and `scripts/bench_history.py`
renders across rounds (`CLUSTER_r*.json`). An unavailable distributed
runtime produces a clean `"status": "unavailable"` artifact and exit 0
(the bench.py cpu-fallback discipline) — never an rc=124 hang.

Usage:
  python scripts/cluster_smoke.py --smoke            # 2 hosts, CI size
  python scripts/cluster_smoke.py --hosts 4 --steps 12 --out CLUSTER.json
"""

import argparse
import json
import os
import pathlib
import shutil
import subprocess
import sys
import tempfile
import time

ROOT = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(ROOT))


def _env():
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env.setdefault("BMT_SYNTH_TRAIN", "512")
    env.setdefault("BMT_SYNTH_TEST", "128")
    return env


def _launch(resdir, hosts, steps, extra, timeout):
    cmd = [sys.executable, "-m", "byzantinemomentum_tpu.cluster",
           "--hosts", str(hosts), "--result-directory", str(resdir),
           "--nb-steps", str(steps), "--checkpoint-delta", "2",
           "--poll", "0.1", *extra]
    t0 = time.monotonic()
    proc = subprocess.run(cmd, cwd=ROOT, env=_env(), capture_output=True,
                          text=True, timeout=timeout)
    elapsed = time.monotonic() - t0
    artifact = None
    try:
        artifact = json.loads((resdir / "CLUSTER.json").read_text())
    except (OSError, ValueError):
        pass
    return proc, artifact, elapsed


def main(argv=None):
    parser = argparse.ArgumentParser(prog="cluster_smoke")
    parser.add_argument("--hosts", type=int, default=2)
    parser.add_argument("--steps", type=int, default=6)
    parser.add_argument("--smoke", action="store_true",
                        help="CI preset: 2 hosts, 6 steps")
    parser.add_argument("--kill-step", type=int, default=None,
                        help="cluster step at which the chaos plan kills "
                             "a host (default: steps // 2)")
    parser.add_argument("--workdir", type=str, default=None,
                        help="keep the run directories here instead of a "
                             "temp dir")
    parser.add_argument("--out", type=str, default=None,
                        help="artifact path (default: <workdir>/"
                             "CLUSTER.json; pass the repo root to commit "
                             "a round)")
    parser.add_argument("--timeout", type=float, default=1200.0,
                        help="bound on EACH fleet run in seconds")
    args = parser.parse_args(argv)
    if args.smoke:
        args.hosts, args.steps = 2, 6
    if args.hosts < 2:
        parser.error("the recovery proof needs at least 2 hosts")
    # Default kill step: mid-run, and ODD so it lands between the
    # checkpoint-delta-2 milestones — the recovery then provably
    # re-executes at least one step instead of resuming for free
    kill_step = args.kill_step
    if kill_step is None:
        kill_step = max(1, args.steps // 2)
        kill_step += 1 - (kill_step % 2)

    workdir = pathlib.Path(args.workdir) if args.workdir else pathlib.Path(
        tempfile.mkdtemp(prefix="bmt-cluster-smoke-"))
    workdir.mkdir(parents=True, exist_ok=True)
    out = pathlib.Path(args.out) if args.out else workdir / "CLUSTER.json"

    def finish(payload, rc):
        out.parent.mkdir(parents=True, exist_ok=True)
        out.write_text(json.dumps(payload, indent="\t", sort_keys=True)
                       + "\n")
        print("cluster-smoke: " + json.dumps(
            {"status": payload.get("status"),
             "hosts": payload.get("hosts"),
             "steps_per_sec": payload.get("steps_per_sec"),
             "recovery_steps": (payload.get("recovery") or {}).get(
                 "recovery_steps"),
             "bit_identical": payload.get("bit_identical"),
             "artifact": str(out)}), flush=True)
        if args.workdir is None and rc == 0:
            shutil.rmtree(workdir, ignore_errors=True)
        return rc

    # --- fleet A: uninterrupted (throughput + census + zero-recompile) --- #
    full_dir = workdir / "full"
    proc, full_art, _ = _launch(
        full_dir, args.hosts, args.steps,
        ["--recompile-check", "2", "--lattice-census"], args.timeout)
    if full_art is None:
        print(proc.stdout[-2000:] + proc.stderr[-2000:], file=sys.stderr)
        return finish({"kind": "cluster", "hosts": args.hosts,
                       "status": "crashed", "steps_per_sec": None}, 1)
    if full_art.get("status") == "unavailable":
        # Bounded-timeout contract: clean artifact, exit 0, no rc=124
        return finish(full_art, 0)
    if proc.returncode != 0 or full_art.get("status") != "ok":
        print(proc.stdout[-2000:] + proc.stderr[-2000:], file=sys.stderr)
        return finish(dict(full_art, status="failed"), 1)

    # --- fleet B: one host SIGKILLed mid-step, recovered, bit-compared --- #
    from byzantinemomentum_tpu.faults import FaultPlan
    from byzantinemomentum_tpu.faults.plan import device_loss

    chaos_dir = workdir / "chaos"
    plan_path = workdir / "system-fault-plan.json"
    # Kill the highest host index: never the coordinator (host 0), and
    # with >2 hosts the survivors outnumber the dead — the quorum story
    FaultPlan(events=(device_loss(args.hosts - 1, kill_step),)).save(
        plan_path)
    proc, chaos_art, _ = _launch(
        chaos_dir, args.hosts, args.steps,
        ["--fault-plan", str(plan_path), "--auto-resume",
         "--fleet-retries", "2"], args.timeout)
    if proc.returncode != 0 or chaos_art is None \
            or chaos_art.get("status") != "ok":
        print(proc.stdout[-2000:] + proc.stderr[-2000:], file=sys.stderr)
        return finish(dict(chaos_art or {"kind": "cluster"},
                           status="chaos_failed", hosts=args.hosts), 1)

    recovery = chaos_art.get("recovery") or {}
    if not recovery.get("events"):
        return finish(dict(chaos_art, status="no_kill_observed"), 1)

    try:
        identical = ((full_dir / "study").read_bytes()
                     == (chaos_dir / "study").read_bytes())
    except OSError:
        identical = False

    artifact = dict(full_art)
    artifact["recovery"] = recovery
    artifact["bit_identical"] = bool(identical)
    artifact["kill_step"] = kill_step
    if not identical:
        artifact["status"] = "divergent_resume"
        return finish(artifact, 1)
    return finish(artifact, 0)


if __name__ == "__main__":
    sys.exit(main())
