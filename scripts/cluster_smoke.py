#!/usr/bin/env python3
"""The multi-host chaos acceptance, as one command: an N-host CPU-backend
fleet trains uninterrupted; a second identical fleet has one host
SIGKILLed mid-step by the system-level FaultPlan, recovers through the
launcher (manifest-agreed restart step, off-slice mirror, auto-resume —
the dead host's local directory is deleted at teardown), and the resumed
study CSV must be BIT-IDENTICAL to the uninterrupted run's.

Writes a `CLUSTER.json` artifact (`"kind": "cluster"`) merging the
uninterrupted fleet's throughput + census/zero-recompile verdicts with
the chaos fleet's recovery record and the bit-identity bit — the
artifact `scripts/bench_compare.py` gates and `scripts/bench_history.py`
renders across rounds (`CLUSTER_r*.json`). An unavailable distributed
runtime produces a clean `"status": "unavailable"` artifact and exit 0
(the bench.py cpu-fallback discipline) — never an rc=124 hang.

Two opt-in elastic rounds ride along (PR 17; `--smoke` alone stays the
legacy pair, bit-identity included):

* `--shrink-round` — an N-host fleet (default 4) loses a host to the
  chaos plan and, under `--elastic`, resumes at the SURVIVOR count from
  the off-slice mirror: quorum `f` re-clamped, `nb_workers`/study split
  re-derived, the shrink persisted as a versioned membership event, and
  the study CSV well-formed through the shrink (divergence past the
  shrink is by design — the fleet got smaller).
* `--straggler-round` — a host is SIGSTOP'd twice by `straggle` chaos
  windows: a short window it must SURVIVE (stale -> suspect ->
  recovered, zero kills inside the bounded wait) and a long window that
  must get it KILLED within the bound (blamed by not-scheduling
  process-state evidence, never a wedged hostage), after which the
  elastic shrink completes the run one host smaller.

Usage:
  python scripts/cluster_smoke.py --smoke            # 2 hosts, CI size
  python scripts/cluster_smoke.py --hosts 4 --steps 12 --out CLUSTER.json
  python scripts/cluster_smoke.py --smoke --shrink-round --straggler-round
"""

import argparse
import json
import os
import pathlib
import shutil
import subprocess
import sys
import tempfile
import time

ROOT = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(ROOT))


def _env():
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env.setdefault("BMT_SYNTH_TRAIN", "512")
    env.setdefault("BMT_SYNTH_TEST", "128")
    return env


def _launch(resdir, hosts, steps, extra, timeout):
    cmd = [sys.executable, "-m", "byzantinemomentum_tpu.cluster",
           "--hosts", str(hosts), "--result-directory", str(resdir),
           "--nb-steps", str(steps), "--checkpoint-delta", "2",
           "--poll", "0.1", *extra]
    t0 = time.monotonic()
    proc = subprocess.run(cmd, cwd=ROOT, env=_env(), capture_output=True,
                          text=True, timeout=timeout)
    elapsed = time.monotonic() - t0
    artifact = None
    try:
        artifact = json.loads((resdir / "CLUSTER.json").read_text())
    except (OSError, ValueError):
        pass
    return proc, artifact, elapsed


def _check_study(resdir, steps):
    """The study CSV's well-formedness verdict: `(rows, problem)`. The
    header and every row must carry the full schema, steps must run
    contiguously 0..steps-1 — a shrunk fleet DIVERGES numerically past
    the shrink (smaller quorum, re-split workers), but the trajectory it
    writes must still be one contiguous, duplicate-free table."""
    from byzantinemomentum_tpu.engine import STUDY_COLUMNS

    try:
        text = (resdir / "study").read_text()
    except OSError as err:
        return 0, f"study CSV unreadable: {err}"
    lines = [line for line in text.split(os.linesep) if line]
    header = "# " + "\t".join(STUDY_COLUMNS)
    if not lines or lines[0] != header:
        return 0, "study CSV header mismatch"
    seen = []
    for line in lines[1:]:
        fields = line.split("\t")
        if len(fields) != len(STUDY_COLUMNS):
            return len(seen), (f"study row with {len(fields)} fields "
                               f"(want {len(STUDY_COLUMNS)})")
        try:
            seen.append(int(fields[0]))
        except ValueError:
            return len(seen), f"unparsable step field {fields[0]!r}"
    if seen != list(range(steps)):
        return len(seen), (f"study steps not contiguous 0..{steps - 1}: "
                           f"{seen[:4]}..{seen[-4:] if seen else []}")
    return len(seen), None


def _shrink_round(args, workdir):
    """The partial-fleet survival proof. Returns (block, problem)."""
    from byzantinemomentum_tpu.cluster import elastic
    from byzantinemomentum_tpu.faults import FaultPlan
    from byzantinemomentum_tpu.faults.plan import device_loss
    from byzantinemomentum_tpu.serve.fleet import ring

    hosts, steps, kill_step = args.shrink_hosts, 8, 5
    nb = 2 * hosts  # uniform splits at EVERY survivor width >= 1
    base = {"hosts": hosts, "nb_workers": nb, "nb_decl_byz": 2,
            "nb_real_byz": 2, "nb_for_study": nb, "gar": "median"}
    rdir = workdir / "shrink"
    plan_path = workdir / "shrink-fault-plan.json"
    FaultPlan(events=(device_loss(hosts - 1, kill_step),)).save(plan_path)
    proc, art, _ = _launch(
        rdir, hosts, steps,
        ["--fault-plan", str(plan_path), "--auto-resume",
         "--fleet-retries", "2", "--elastic",
         "--nb-workers", str(nb), "--nb-for-study", str(nb)],
        args.timeout)
    if art is None or proc.returncode != 0 or art.get("status") != "ok":
        print(proc.stdout[-2000:] + proc.stderr[-2000:], file=sys.stderr)
        return None, (f"shrink fleet failed (rc={proc.returncode}, "
                      f"status={(art or {}).get('status')})")
    elastic_block = art.get("elastic") or {}
    shrinks = elastic_block.get("shrinks") or []
    if elastic_block.get("initial_hosts") != hosts \
            or elastic_block.get("final_hosts") != hosts - 1 \
            or len(shrinks) != 1:
        return None, f"expected exactly one shrink {hosts}->{hosts - 1}, " \
                     f"got {elastic_block}"
    want = elastic.shrunk_spec(base, hosts - 1)
    if shrinks[0].get("config") != want:
        return None, (f"shrunk config {shrinks[0].get('config')} != "
                      f"re-derived {want}")
    payload = ring.read_fleet_manifest(rdir)
    member = ring.Membership.replay(payload) if payload else None
    if member is None or len(member.shards) != hosts - 1 \
            or member.version != elastic_block.get("membership_version"):
        return None, "fleet.json membership does not replay to the " \
                     "shrunken fleet"
    rows, problem = _check_study(rdir, steps)
    if problem is not None:
        return None, problem
    recovery = art.get("recovery") or {}
    return {"status": "ok", "hosts": hosts, "final_hosts": hosts - 1,
            "kill_step": kill_step,
            "died_at_step": shrinks[0].get("died_at_step"),
            "recovery_steps": recovery.get("recovery_steps"),
            "config": want,
            "membership_version": member.version,
            "study_rows": rows}, None


def _straggler_round(args, workdir):
    """The bounded-wait straggler proof. Returns (block, problem)."""
    from byzantinemomentum_tpu.faults import FaultPlan
    from byzantinemomentum_tpu.faults.plan import straggle

    hosts, steps = args.straggler_hosts, 10
    nb = 2 * hosts
    victim = hosts - 1
    wait = args.straggler_wait
    # Short window: strictly inside the bound — the host must RECOVER
    # (stale -> suspect -> fresh heartbeat), zero kills. Long window:
    # far past it — the host must be killed at ~stale+bound, the pending
    # SIGCONT cancelled, the fleet shrunk and completed.
    short_s = wait / 2.0
    rdir = workdir / "straggler"
    plan_path = workdir / "straggle-fault-plan.json"
    FaultPlan(events=(straggle(victim, 2, short_s),
                      straggle(victim, 6, 30 * wait))).save(plan_path)
    proc, art, _ = _launch(
        rdir, hosts, steps,
        ["--fault-plan", str(plan_path), "--auto-resume",
         "--fleet-retries", "2", "--elastic",
         "--heartbeat-stale", "2.0",
         "--straggler-wait", str(wait),
         "--nb-workers", str(nb), "--nb-for-study", str(nb)],
        args.timeout)
    if art is None or proc.returncode != 0 or art.get("status") != "ok":
        print(proc.stdout[-2000:] + proc.stderr[-2000:], file=sys.stderr)
        return None, (f"straggler fleet failed (rc={proc.returncode}, "
                      f"status={(art or {}).get('status')})")
    straggler = art.get("straggler") or {}
    kills = straggler.get("kills") or []
    recoveries = straggler.get("recoveries") or []
    if len(kills) != 1:
        return None, (f"expected exactly one straggler kill, got "
                      f"{kills} (a merely-slow host must never die "
                      f"inside the bound)")
    kill = kills[0]
    if kill.get("host") != victim:
        return None, f"killed host {kill.get('host')}, not the " \
                     f"SIGSTOP'd host {victim}"
    # The bounded wait, with 1-core scheduling slack on top: the kill
    # must land at ~(stale edge + bound), never "eventually"
    if not kill.get("suspect_s") or kill["suspect_s"] > wait + 6.0:
        return None, f"kill outside the bounded wait: {kill}"
    if not any(r.get("host") == victim and r.get("reason") == "stale"
               for r in recoveries):
        return None, (f"short straggle window did not recover "
                      f"(recoveries={recoveries})")
    windows = art.get("straggle_windows") or {}
    if not windows.get("resumed") or not windows.get("cancelled"):
        return None, f"straggle windows not exercised: {windows}"
    elastic_block = art.get("elastic") or {}
    if elastic_block.get("final_hosts") != hosts - 1:
        return None, f"straggler kill did not shrink the fleet: " \
                     f"{elastic_block}"
    recovery = art.get("recovery") or {}
    return {"status": "ok", "hosts": hosts, "final_hosts": hosts - 1,
            "wait_s": straggler.get("wait_s"),
            "kills": len(kills), "killed_host": kill.get("host"),
            "kill_reason": kill.get("reason"),
            "not_scheduling": kill.get("not_scheduling"),
            "suspect_s": kill.get("suspect_s"),
            "recoveries": len(recoveries),
            "windows": windows,
            "recovery_steps": recovery.get("recovery_steps")}, None


def main(argv=None):
    parser = argparse.ArgumentParser(prog="cluster_smoke")
    parser.add_argument("--hosts", type=int, default=2)
    parser.add_argument("--steps", type=int, default=6)
    parser.add_argument("--smoke", action="store_true",
                        help="CI preset: 2 hosts, 6 steps")
    parser.add_argument("--kill-step", type=int, default=None,
                        help="cluster step at which the chaos plan kills "
                             "a host (default: steps // 2)")
    parser.add_argument("--workdir", type=str, default=None,
                        help="keep the run directories here instead of a "
                             "temp dir")
    parser.add_argument("--out", type=str, default=None,
                        help="artifact path (default: <workdir>/"
                             "CLUSTER.json; pass the repo root to commit "
                             "a round)")
    parser.add_argument("--timeout", type=float, default=1200.0,
                        help="bound on EACH fleet run in seconds")
    parser.add_argument("--shrink-round", action="store_true",
                        help="elastic partial-fleet survival round: kill "
                             "one host, resume at the SURVIVOR count")
    parser.add_argument("--shrink-hosts", type=int, default=4,
                        help="fleet size of the shrink round")
    parser.add_argument("--straggler-round", action="store_true",
                        help="bounded-wait straggler round: SIGSTOP "
                             "windows, one survived, one killed-and-"
                             "shrunk")
    parser.add_argument("--straggler-hosts", type=int, default=3,
                        help="fleet size of the straggler round")
    parser.add_argument("--straggler-wait", type=float, default=8.0,
                        help="bounded wait of the straggler round's "
                             "policy in seconds")
    args = parser.parse_args(argv)
    if args.smoke:
        args.hosts, args.steps = 2, 6
    if args.hosts < 2:
        parser.error("the recovery proof needs at least 2 hosts")
    if args.shrink_round and args.shrink_hosts < 3:
        parser.error("the shrink round needs at least 3 hosts (the "
                     "survivors must still be a fleet)")
    if args.straggler_round and args.straggler_hosts < 2:
        parser.error("the straggler round needs at least 2 hosts")
    # Default kill step: mid-run, and ODD so it lands between the
    # checkpoint-delta-2 milestones — the recovery then provably
    # re-executes at least one step instead of resuming for free
    kill_step = args.kill_step
    if kill_step is None:
        kill_step = max(1, args.steps // 2)
        kill_step += 1 - (kill_step % 2)

    workdir = pathlib.Path(args.workdir) if args.workdir else pathlib.Path(
        tempfile.mkdtemp(prefix="bmt-cluster-smoke-"))
    workdir.mkdir(parents=True, exist_ok=True)
    out = pathlib.Path(args.out) if args.out else workdir / "CLUSTER.json"

    def finish(payload, rc):
        out.parent.mkdir(parents=True, exist_ok=True)
        out.write_text(json.dumps(payload, indent="\t", sort_keys=True)
                       + "\n")
        line = {"status": payload.get("status"),
                "hosts": payload.get("hosts"),
                "steps_per_sec": payload.get("steps_per_sec"),
                "recovery_steps": (payload.get("recovery") or {}).get(
                    "recovery_steps"),
                "bit_identical": payload.get("bit_identical"),
                "artifact": str(out)}
        if payload.get("shrink_round") is not None:
            line["shrink_recovery_steps"] = payload["shrink_round"].get(
                "recovery_steps")
        if payload.get("straggler_round") is not None:
            line["straggler_kills"] = payload["straggler_round"].get(
                "kills")
        print("cluster-smoke: " + json.dumps(line), flush=True)
        if args.workdir is None and rc == 0:
            shutil.rmtree(workdir, ignore_errors=True)
        return rc

    # --- fleet A: uninterrupted (throughput + census + zero-recompile) --- #
    full_dir = workdir / "full"
    proc, full_art, _ = _launch(
        full_dir, args.hosts, args.steps,
        ["--recompile-check", "2", "--lattice-census"], args.timeout)
    if full_art is None:
        print(proc.stdout[-2000:] + proc.stderr[-2000:], file=sys.stderr)
        return finish({"kind": "cluster", "hosts": args.hosts,
                       "status": "crashed", "steps_per_sec": None}, 1)
    if full_art.get("status") == "unavailable":
        # Bounded-timeout contract: clean artifact, exit 0, no rc=124
        return finish(full_art, 0)
    if proc.returncode != 0 or full_art.get("status") != "ok":
        print(proc.stdout[-2000:] + proc.stderr[-2000:], file=sys.stderr)
        return finish(dict(full_art, status="failed"), 1)

    # --- fleet B: one host SIGKILLed mid-step, recovered, bit-compared --- #
    from byzantinemomentum_tpu.faults import FaultPlan
    from byzantinemomentum_tpu.faults.plan import device_loss

    chaos_dir = workdir / "chaos"
    plan_path = workdir / "system-fault-plan.json"
    # Kill the highest host index: never the coordinator (host 0), and
    # with >2 hosts the survivors outnumber the dead — the quorum story
    FaultPlan(events=(device_loss(args.hosts - 1, kill_step),)).save(
        plan_path)
    proc, chaos_art, _ = _launch(
        chaos_dir, args.hosts, args.steps,
        ["--fault-plan", str(plan_path), "--auto-resume",
         "--fleet-retries", "2"], args.timeout)
    if proc.returncode != 0 or chaos_art is None \
            or chaos_art.get("status") != "ok":
        print(proc.stdout[-2000:] + proc.stderr[-2000:], file=sys.stderr)
        return finish(dict(chaos_art or {"kind": "cluster"},
                           status="chaos_failed", hosts=args.hosts), 1)

    recovery = chaos_art.get("recovery") or {}
    if not recovery.get("events"):
        return finish(dict(chaos_art, status="no_kill_observed"), 1)

    try:
        identical = ((full_dir / "study").read_bytes()
                     == (chaos_dir / "study").read_bytes())
    except OSError:
        identical = False

    artifact = dict(full_art)
    artifact["recovery"] = recovery
    artifact["bit_identical"] = bool(identical)
    artifact["kill_step"] = kill_step
    if not identical:
        artifact["status"] = "divergent_resume"
        return finish(artifact, 1)

    # --- opt-in elastic rounds: shrink survival + straggler policy --- #
    if args.shrink_round:
        block, problem = _shrink_round(args, workdir)
        if problem is not None:
            print(f"cluster-smoke: shrink round: {problem}",
                  file=sys.stderr)
            artifact["shrink_round"] = {"status": "failed",
                                        "problem": problem}
            return finish(dict(artifact, status="shrink_failed"), 1)
        artifact["shrink_round"] = block
    if args.straggler_round:
        block, problem = _straggler_round(args, workdir)
        if problem is not None:
            print(f"cluster-smoke: straggler round: {problem}",
                  file=sys.stderr)
            artifact["straggler_round"] = {"status": "failed",
                                           "problem": problem}
            return finish(dict(artifact, status="straggler_failed"), 1)
        artifact["straggler_round"] = block
    return finish(artifact, 0)


if __name__ == "__main__":
    sys.exit(main())
