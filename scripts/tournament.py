#!/usr/bin/env python3
"""Attack-vs-defense tournament runner (`byzantinemomentum_tpu/arena/`).

Full mode sweeps every registered attack x every first-tier GAR x
quarantine {on, off} in train mode plus the serve-mode Sybil admission
pair, and writes the resilience scoreboard `TOURNAMENT_r{N}.json` at the
repo root (the committed-artifact convention of BENCH_r*/ATTRIB_r*;
`scripts/bench_history.py` renders the trajectory).

`--smoke` runs the CI grid — 2 attacks x 2 GARs + a short Sybil pair —
with the zero-recompile assertion armed
(`analysis/contracts.py::assert_recompile_budget` over changing
quarantine masks), exits non-zero on any broken invariant, and prints
one machine-readable summary line for the tier harness
(`scripts/run_test_tiers.py`).

Usage:
  python scripts/tournament.py --round 11           # full grid artifact
  python scripts/tournament.py --smoke              # CI smoke
"""

import argparse
import json
import os
import pathlib
import sys
import time

ROOT = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(ROOT))

# The grid is CPU-sized (probe engine); never wait on a TPU tunnel
os.environ.setdefault("JAX_PLATFORMS", "cpu")

SMOKE_GARS = ("krum", "median")
SMOKE_ATTACKS = ("alie", "framing")


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="tournament",
        description="attack x GAR x quarantine resilience scoreboard")
    parser.add_argument("--round", type=int, default=None,
                        help="write TOURNAMENT_r{N}.json at the repo root")
    parser.add_argument("--smoke", action="store_true",
                        help="CI smoke: 2x2 grid + recompile assertion, "
                             "no artifact unless --out/--round is given")
    parser.add_argument("--steps", type=int, default=None,
                        help="train steps per cell (default: 40 smoke, "
                             "80 full)")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--out", type=str, default=None,
                        help="explicit artifact path (overrides --round)")
    args = parser.parse_args(argv)

    from byzantinemomentum_tpu.arena import tournament

    start = time.monotonic()
    if args.smoke:
        roster = [(a, a, {}, 0.0) for a in SMOKE_ATTACKS]
        scoreboard = tournament.run_tournament(
            gars=SMOKE_GARS, roster=roster,
            steps=args.steps or 40, seed=args.seed,
            serve_requests=18, recompile_check=True, log=print)
    else:
        scoreboard = tournament.run_tournament(
            steps=args.steps or 80, seed=args.seed,
            recompile_check=True, log=print)
    scoreboard["elapsed_s"] = round(time.monotonic() - start, 1)
    if args.round is not None:
        scoreboard["round"] = args.round

    summary = scoreboard["summary"]
    failures = []
    if summary["framing_honest_evictions"]:
        failures.append(
            f"framing evicted {summary['framing_honest_evictions']} honest "
            f"worker(s) — the hysteresis contract broke")
    if args.smoke:
        # The smoke's own green conditions beyond the recompile assertion
        # (which already raised if violated): the Sybil pair must show
        # admission catching what slips through without it
        sybil = summary["sybil"]
        if not (sybil.get("shift_tail_on", 1e9)
                < sybil.get("shift_tail_off", 0.0)):
            failures.append(f"sybil admission pair inverted: {sybil}")
        if sybil.get("honest_masked", 1):
            failures.append(f"sybil admission masked honest ids: {sybil}")
    else:
        if not summary["selection_gars_dominated"]:
            failures.append(
                "quarantine-on dominates quarantine-off on NO selection "
                "GAR against the adaptive attacks")

    path = None
    if args.out or args.round is not None:
        path = pathlib.Path(args.out) if args.out else (
            ROOT / f"TOURNAMENT_r{args.round:02d}.json")
        path.write_text(json.dumps(scoreboard, indent=1) + "\n")

    print("tournament: " + json.dumps({
        "cells": len(scoreboard["train_cells"]),
        "serve_cells": len(scoreboard["serve_cells"]),
        "elapsed_s": scoreboard["elapsed_s"],
        "artifact": path.name if path else None,
        "summary": summary,
        "green": not failures,
    }, sort_keys=True))
    for failure in failures:
        print(f"tournament FAILURE: {failure}", file=sys.stderr)
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
